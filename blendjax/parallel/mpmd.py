"""MPMD pipeline parallelism: stage *processes* with 1F1B microbatch interleaving.

:mod:`blendjax.parallel.pipeline` is the SPMD leg — every stage lives
inside one jit on one mesh, activations ride ``lax.ppermute`` over ICI.
This module is the MPMD leg the scaling literature names (Scaling DL
Training with MPMD Pipeline Parallelism, arXiv:2412.14374; Podracer,
arXiv:2104.06272): N independent **stage processes**
(``python -m blendjax.parallel.stage``), each owning one contiguous
slice of the model's layers and its own jitted forward/backward,
exchanging activation and gradient microbatches over
:class:`~blendjax.btt.transport.RpcChannel` — ShmRPC when driver and
stages share a host, ZMQ across hosts (the ``host_token`` refusal is
the seam) — as raw-buffer wire frames under the BTMID exactly-once
discipline every other tier speaks.

Topology (see docs/pipeline.md)::

    driver ──fwd(u,mb,x)──> stage 0 ──fwd──> stage 1 ──fwd──> stage N-1
    driver ──────────────tgt(u,mb,t)────────────────────────> stage N-1
    stage 0 <──bwd── stage 1 <──bwd── ... <──bwd(u,mb,g)───── stage N-1

The schedule is 1F1B by construction rather than by a scheduler: each
stage computes a record the moment it arrives, so stage k runs
microbatch m's forward while stage k-1 runs m+1's, and the last stage
backpropagates a microbatch the same instant its forward completes
(forward+loss+backward fused in one jitted unit).  The driver's bounded
feed window is the bubble-schedule backpressure: a full pipeline parks
the feed (``pipe_feed_parks``) instead of allocating.

Model family: the policy MLP (:func:`blendjax.models.policy.init`) —
``layers[0]`` is the input projection (owned by stage 0), the
``n_layers`` wire-width tanh layers split contiguously across stages,
and the ``out`` head + loss live on the last stage.  That split is
EXACTLY :func:`~blendjax.parallel.pipeline.make_pipeline_train`'s
``in_proj``/``stage_fn``/``out_proj`` factoring, which is what makes
the single-process in-jit reference a bit-level numerics lock for the
multi-process schedule (``tests/test_mpmd.py``).

Crash-exactness: stages apply plain SGD at update boundaries only,
checkpoint through :class:`blendjax.utils.checkpoint.CheckpointManager`
(the PR-15 machinery) every ``ckpt_every`` commits, and a
SIGKILL+respawn (``FleetWatchdog(restart=True)`` over
:class:`StageFleet`) is healed by the driver: it reconciles every
stage's ``applied`` counter, rolls stages that committed the in-flight
update back to the common boundary, and replays the update from its
held microbatches — in-flight records re-sent under the same mid are
deduped by the stage reply cache, so no microbatch is lost or applied
twice.
"""

from __future__ import annotations

import json
import logging
import os
import subprocess
import sys
import threading
import time
from collections import OrderedDict

import numpy as np

from blendjax import wire
from blendjax.btt import shm_rpc
from blendjax.btt.faults import FaultPolicy
from blendjax.utils.timing import EventCounters, StageTimer

logger = logging.getLogger("blendjax")

#: checkpoint metadata format tag (stage checkpoints are plain pytrees;
#: the tag rides the directory name, not the file)
SPEC_KEYS = ("family", "d_in", "wire", "d_out", "n_layers", "n_procs",
             "lr", "seed")

#: default feed window (microbatches in flight past stage 0) when the
#: spec does not override: deep enough to keep every stage busy
#: (the 1F1B steady state needs ~n in flight), shallow enough that a
#: full pipeline parks the feed instead of queueing unboundedly.
def default_window(n_procs):
    return 2 * int(n_procs)


class PipeRpcError(ConnectionError):
    """Transport-level failure talking to a stage (timeout, circuit
    open) — the retryable class under the driver's FaultPolicy."""


class PipeRestart(RuntimeError):
    """The in-flight update cannot complete against the current stage
    incarnations (a stage died / answered ``restart_needed``): the
    driver reconciles and replays the update."""


def normalize_spec(spec):
    """Validate and default a pipeline spec dict.

    Keys: ``family`` (``"mse"`` regression stand-in | ``"pg"`` the
    learner's importance-weighted policy gradient), ``d_in``, ``wire``
    (inter-stage activation width), ``d_out``, ``n_layers`` (wire-width
    tanh layers split across stages; ``layers[0]`` — the d_in->wire
    input projection — is pinned to stage 0 on top of its slice),
    ``n_procs``, ``lr`` (per-stage SGD), ``seed``.
    """
    s = dict(spec)
    s.setdefault("family", "mse")
    s.setdefault("lr", 1e-2)
    s.setdefault("seed", 0)
    missing = [k for k in SPEC_KEYS if k not in s]
    if missing:
        raise ValueError(f"pipeline spec missing keys {missing}")
    if s["family"] not in ("mse", "pg"):
        raise ValueError(f"unknown pipeline family {s['family']!r}")
    if int(s["n_procs"]) < 1:
        raise ValueError("n_procs must be >= 1")
    if int(s["n_layers"]) < 1:
        raise ValueError("n_layers must be >= 1")
    for k in ("d_in", "wire", "d_out", "n_layers", "n_procs", "seed"):
        s[k] = int(s[k])
    s["lr"] = float(s["lr"])
    return s


def stage_slice(n_layers, n_procs, proc_index):
    """Contiguous [lo, hi) of the ``n_layers`` wire-width layers owned
    by stage ``proc_index`` (remainder layers go to the EARLY stages,
    which also carry the input projection — front-loading keeps the
    last stage's fused fwd+loss+bwd unit from being the straggler)."""
    base, rem = divmod(int(n_layers), int(n_procs))
    lo = proc_index * base + min(proc_index, rem)
    hi = lo + base + (1 if proc_index < rem else 0)
    return lo, hi


def build_full_params(spec):
    """The full model params, deterministic from ``spec['seed']`` — the
    ONE source the driver's reference, every stage, and a respawned
    stage's rollback-to-zero all build from."""
    import jax

    from blendjax.models import policy

    return policy.init(
        jax.random.PRNGKey(spec["seed"]), spec["d_in"], spec["d_out"],
        hidden=(spec["wire"],) * (spec["n_layers"] + 1),
    )


def stage_local_params(full, spec, proc_index):
    """Stage ``proc_index``'s slice of the full param tree."""
    lo, hi = stage_slice(spec["n_layers"], spec["n_procs"], proc_index)
    local = {"layers": [full["layers"][1 + i] for i in range(lo, hi)]}
    if proc_index == 0:
        local["in"] = full["layers"][0]
    if proc_index == spec["n_procs"] - 1:
        local["out"] = full["out"]
    return local


def assemble_full_params(locals_by_stage, spec):
    """Inverse of :func:`stage_local_params` over every stage."""
    full = {"layers": [None] * (spec["n_layers"] + 1), "out": None}
    for p, local in enumerate(locals_by_stage):
        lo, hi = stage_slice(spec["n_layers"], spec["n_procs"], p)
        for i in range(lo, hi):
            full["layers"][1 + i] = local["layers"][i - lo]
        if p == 0:
            full["layers"][0] = local["in"]
        if p == spec["n_procs"] - 1:
            full["out"] = local["out"]
    return full


def make_loss_fn(family):
    """``loss(pred, tgt_dict) -> scalar`` for a family; ``tgt_dict`` is
    the microbatched target record the driver pushes to the last stage
    (``{"y"}`` for mse; ``{"action", "adv", "w"}`` for pg — advantage
    pre-normalized over the FULL batch on the driver so equal-size
    microbatch means average to the full-batch loss exactly)."""
    import jax
    import jax.numpy as jnp

    if family == "mse":
        def loss(pred, tgt):
            return jnp.mean((pred - tgt["y"]) ** 2)
    else:
        def loss(pred, tgt):
            lp = jax.nn.log_softmax(pred)
            logp = jnp.take_along_axis(
                lp, tgt["action"][..., None].astype(jnp.int32), axis=-1
            )[..., 0]
            return -jnp.mean(tgt["w"] * logp * tgt["adv"])

    return loss


def reference_pieces(spec):
    """(in_proj, stage_fn, out_proj, loss_fn) factored EXACTLY like the
    MPMD stage split, for :func:`~blendjax.parallel.pipeline.
    make_pipeline_train` — the numerics-lock reference.  Requires
    ``n_layers % n_procs == 0`` (stacked stage params must agree in
    shape)."""
    import jax.numpy as jnp

    from blendjax.models.layers import dense_apply

    if spec["n_layers"] % spec["n_procs"]:
        raise ValueError(
            f"reference factoring needs n_layers ({spec['n_layers']}) "
            f"divisible by n_procs ({spec['n_procs']})"
        )
    per = spec["n_layers"] // spec["n_procs"]

    def in_proj(ep, x):
        return jnp.tanh(dense_apply(ep, x))

    def stage_fn(sp, x):
        for i in range(per):
            layer = {"w": sp["w"][i], "b": sp["b"][i]}
            x = jnp.tanh(dense_apply(layer, x))
        return x

    def out_proj(rp, x):
        return dense_apply(rp, x)

    return in_proj, stage_fn, out_proj, make_loss_fn(spec["family"])


def reference_stacked(full, spec):
    """(stacked_stage_params, proj_params) for the reference factoring,
    from the same full param tree the stages split."""
    import jax.numpy as jnp

    per = spec["n_layers"] // spec["n_procs"]
    stages = []
    for p in range(spec["n_procs"]):
        lo = p * per
        stages.append({
            "w": jnp.stack([full["layers"][1 + lo + i]["w"]
                            for i in range(per)]),
            "b": jnp.stack([full["layers"][1 + lo + i]["b"]
                            for i in range(per)]),
        })
    from blendjax.parallel.pipeline import stack_stage_params

    stacked = stack_stage_params(stages)
    return stacked, (full["layers"][0], full["out"])


# ---------------------------------------------------------------------------
# the stage server
# ---------------------------------------------------------------------------


class MpmdStage:
    """One pipeline stage: a REP server (plus the ShmRPC doorbell in
    the same poller, exactly like the replay shard) owning its layer
    slice and jitted compute, pushing activations downstream and
    gradient cotangents upstream through :class:`AsyncPusher`s.

    Exactly-once: every mutating command's reply is cached by its
    BTMID, and fwd/bwd/tgt records are additionally deduped by
    ``(update, mb)`` — a neighbor's same-mid resend after a lost ack
    re-buys the cached ack, never a second compute
    (``pipe_dup_records``).
    """

    def __init__(self, address, spec, proc_index, *,
                 prev_address=None, next_address=None, shm_base=None,
                 ckpt_dir=None, ckpt_every=1, work_us=0,
                 counters=None, context=None):
        import zmq

        self.spec = normalize_spec(spec)
        self.proc_index = int(proc_index)
        self.n_procs = self.spec["n_procs"]
        if not (0 <= self.proc_index < self.n_procs):
            raise ValueError(
                f"proc_index {proc_index} out of range for "
                f"{self.n_procs} procs"
            )
        self.is_first = self.proc_index == 0
        self.is_last = self.proc_index == self.n_procs - 1
        self.prev_address = prev_address
        self.next_address = next_address
        self.work_us = int(work_us)
        self.counters = counters if counters is not None else EventCounters()
        self.timer = StageTimer()
        #: a fresh token per process start: the driver detects respawns
        #: (and counts ``pipe_stage_respawns``) by watching it change
        self.incarnation = os.urandom(4).hex()

        self._build_compute()
        self._applied = 0
        self._last_loss = None
        self.restored_from = None
        self._ckpt_every = max(0, int(ckpt_every))
        self._mgr = None
        if ckpt_dir:
            from blendjax.utils.checkpoint import CheckpointManager

            self._mgr = CheckpointManager(
                os.path.join(ckpt_dir, f"stage_{self.proc_index:02d}"),
                max_to_keep=4,
            )
            step = self._mgr.latest_step()
            if step is not None:
                self._params = self._mgr.restore(
                    {"params": self._params}
                )["params"]
                self._applied = step
                self.restored_from = step
                self.counters.incr("pipe_ckpt_restores")
                logger.info(
                    "pipe stage %d restored checkpoint update %d",
                    self.proc_index, step,
                )

        self._reset_accum()
        self._cur_update = None
        self._m = 0
        self._reply_cache = OrderedDict()

        self._ctx = context or zmq.Context.instance()
        self._sock = self._ctx.socket(zmq.REP)
        self._sock.setsockopt(zmq.LINGER, 0)
        if address.endswith(":*") or address.endswith(":0"):
            base = address.rsplit(":", 1)[0]
            port = self._sock.bind_to_random_port(base)
            self.address = f"{base}:{port}"
        else:
            self._sock.bind(address)
            self.address = address
        self._shm = None
        if shm_rpc.enabled():
            self._shm = shm_rpc.ShmRpcServer(
                base=shm_base or shm_rpc.new_base(f"pst{self.proc_index}"),
                counters=self.counters, bytes_counter="pipe_wire_bytes",
                who=f"pipe stage {self.proc_index}",
            )
        # neighbor pushers dial lazily (single-stage pipelines have none)
        self._down = None
        self._up = None

    # -- compute -------------------------------------------------------------

    def _build_compute(self):
        import jax
        import jax.numpy as jnp

        from blendjax.models.layers import dense_apply

        spec = self.spec
        full = build_full_params(spec)
        self._template = stage_local_params(full, spec, self.proc_index)
        self._params = self._template
        lo, hi = stage_slice(spec["n_layers"], spec["n_procs"],
                             self.proc_index)
        #: layer units this stage owns — the benchmark's compute
        #: stand-in sleeps ``work_us`` per unit per direction, so the
        #: 1-proc baseline carries exactly the fleet's total work
        self.n_units = (hi - lo) + (1 if self.is_first else 0) \
            + (1 if self.is_last else 0)
        loss_fn = make_loss_fn(spec["family"])

        def chain(params, x):
            if "in" in params:
                x = jnp.tanh(dense_apply(params["in"], x))
            for layer in params["layers"]:
                x = jnp.tanh(dense_apply(layer, x))
            return x

        def head_loss(params, a, tgt):
            pred = dense_apply(params["out"], chain(params, a))
            return loss_fn(pred, tgt)

        self._fwd = jax.jit(chain)

        def bwd(params, x, g):
            _, vjp = jax.vjp(chain, params, x)
            return vjp(g)

        self._bwd = jax.jit(bwd)

        def last_unit(params, a, tgt):
            loss, (dp, da) = jax.value_and_grad(
                head_loss, argnums=(0, 1)
            )(params, a, tgt)
            return loss, dp, da

        self._last_unit = jax.jit(last_unit)
        self._acc = jax.jit(
            lambda acc, g: jax.tree.map(jnp.add, acc, g)
        )
        self._apply = jax.jit(
            lambda p, g, lr, m: jax.tree.map(
                lambda a, b: a - lr * b / m, p, g
            )
        )

    def _work(self, units):
        if self.work_us:
            time.sleep(self.work_us * units / 1e6)

    def _reset_accum(self):
        self._grads = None
        self._acts = {}
        self._tgts = {}
        self._seen_fwd = set()
        self._seen_bwd = set()
        self._bwd_done = 0
        self._loss_sum = 0.0
        self._ready = False

    # -- neighbor pushers ----------------------------------------------------

    def _pusher_down(self):
        if self._down is None:
            from blendjax.btt.transport import RpcChannel

            self._down = AsyncPusher(
                RpcChannel(self.next_address, context=self._ctx,
                           name=f"pipe-s{self.proc_index}-down"),
                self.counters, name=f"stage{self.proc_index}->down",
            )
        return self._down

    def _pusher_up(self):
        if self._up is None:
            from blendjax.btt.transport import RpcChannel

            self._up = AsyncPusher(
                RpcChannel(self.prev_address, context=self._ctx,
                           name=f"pipe-s{self.proc_index}-up"),
                self.counters, name=f"stage{self.proc_index}->up",
            )
        return self._up

    # -- dispatch ------------------------------------------------------------

    def handle(self, msg):
        cmd = msg.get("cmd")
        mid = msg.get(wire.BTMID_KEY)
        if mid is not None and mid in self._reply_cache:
            self.counters.incr("pipe_dup_records")
            return self._reply_cache[mid]
        try:
            reply = getattr(self, f"_cmd_{cmd}", self._cmd_unknown)(msg)
        except Exception as exc:  # noqa: BLE001 - surfaced to the peer
            if not isinstance(exc, _RestartNeeded):
                logger.exception("pipe stage %d: %r failed",
                                 self.proc_index, cmd)
            reply = {"error": f"{type(exc).__name__}: {exc}"}
        if mid is not None:
            reply[wire.BTMID_KEY] = mid
            if cmd in ("begin", "fwd", "bwd", "tgt", "commit",
                       "rollback"):
                self._reply_cache[mid] = reply
                while len(self._reply_cache) > wire.REPLY_CACHE_DEPTH:
                    self._reply_cache.popitem(last=False)
        return reply

    def _cmd_unknown(self, msg):
        raise ValueError(f"unknown pipe stage command {msg.get('cmd')!r}")

    def _cmd_hello(self, msg):
        return {
            "proc": self.proc_index,
            "procs": self.n_procs,
            "applied": self._applied,
            "incarnation": self.incarnation,
            "restored": self.restored_from,
            "shm": self._shm.info() if self._shm is not None else None,
        }

    def _cmd_stage_info(self, msg):
        return {
            "proc": self.proc_index,
            "applied": self._applied,
            "current": self._cur_update,
            "ready": self._ready,
            "bwd_done": self._bwd_done,
            "incarnation": self.incarnation,
            "counters": self.counters.snapshot(),
        }

    def _check_update(self, u):
        """Gate a data record against the update in progress.  Returns
        True when the record is STALE (an already-committed update — a
        same-mid resend whose original landed before the commit, or a
        neighbor's push that outran an abort): the handler acks it as a
        duplicate so the sender retires it, instead of erroring a
        record the schedule already consumed."""
        if u <= self._applied:
            self.counters.incr("pipe_dup_records")
            return True
        if self._cur_update != u:
            raise _RestartNeeded(
                f"restart_needed: record for update {u} but stage "
                f"{self.proc_index} is at applied={self._applied} "
                f"current={self._cur_update}"
            )
        return False

    def _cmd_begin(self, msg):
        u, m = int(msg["update"]), int(msg["m"])
        if u <= self._applied:
            # a replayed begin after this stage already committed the
            # update (driver recovery races): idempotent no-op
            return {"applied": self._applied, "skip": True}
        if u != self._applied + 1:
            raise _RestartNeeded(
                f"restart_needed: begin {u} but stage {self.proc_index} "
                f"applied={self._applied}"
            )
        if self._cur_update == u and not msg.get("restart"):
            return {"applied": self._applied}
        self._cur_update = u
        self._m = m
        self._reset_accum()
        if msg.get("restart"):
            # drop in-flight pushes of the aborted attempt: the replay
            # re-feeds every record under fresh mids
            for pusher in (self._down, self._up):
                if pusher is not None:
                    pusher.clear()
        return {"applied": self._applied}

    def _cmd_fwd(self, msg):
        u, mb = int(msg["update"]), int(msg["mb"])
        if self._check_update(u):
            return {"ok": True, "stale": True}
        if mb in self._seen_fwd:
            self.counters.incr("pipe_dup_records")
            return {"ok": True, "dup": True}
        self._seen_fwd.add(mb)
        x = np.asarray(msg["x"])
        if self.is_last:
            self._acts[mb] = x
            self._maybe_last(mb)
            return {"ok": True}
        with self.timer.stage("pipe_fwd"):
            y = np.asarray(self._fwd(self._params, x))
            self._work(self.n_units)
        self._acts[mb] = x
        self._pusher_down().push(
            {"cmd": "fwd", "update": u, "mb": mb, "x": y}
        )
        return {"ok": True}

    def _cmd_tgt(self, msg):
        u, mb = int(msg["update"]), int(msg["mb"])
        if self._check_update(u):
            return {"ok": True, "stale": True}
        if mb in self._tgts or mb in self._seen_bwd:
            self.counters.incr("pipe_dup_records")
            return {"ok": True, "dup": True}
        self._tgts[mb] = {k: np.asarray(v)
                          for k, v in msg["tgt"].items()}
        self._maybe_last(mb)
        return {"ok": True}

    def _maybe_last(self, mb):
        """The last stage's fused unit: once microbatch ``mb`` has both
        its activation and its target, run forward+loss+backward in one
        jitted call and push the cotangent upstream — 1F1B's eager
        backward, scheduled by arrival."""
        if mb not in self._acts or mb not in self._tgts \
                or mb in self._seen_bwd:
            return
        self._seen_bwd.add(mb)
        a = self._acts.pop(mb)
        tgt = self._tgts.pop(mb)
        with self.timer.stage("pipe_bwd"):
            loss, dp, da = self._last_unit(self._params, a, tgt)
            self._work(2 * self.n_units)
        self._loss_sum += float(loss)
        self._accumulate(dp)
        if not self.is_first:
            self._pusher_up().push({
                "cmd": "bwd", "update": self._cur_update, "mb": mb,
                "g": np.asarray(da),
            })
        self._note_bwd_done()

    def _cmd_bwd(self, msg):
        u, mb = int(msg["update"]), int(msg["mb"])
        if self._check_update(u):
            return {"ok": True, "stale": True}
        if mb in self._seen_bwd:
            self.counters.incr("pipe_dup_records")
            return {"ok": True, "dup": True}
        if mb not in self._acts:
            raise ValueError(
                f"bwd for microbatch {mb} before its forward on stage "
                f"{self.proc_index}"
            )
        self._seen_bwd.add(mb)
        x = self._acts.pop(mb)
        g = np.asarray(msg["g"])
        with self.timer.stage("pipe_bwd"):
            dp, dx = self._bwd(self._params, x, g)
            self._work(self.n_units)
        self._accumulate(dp)
        if not self.is_first:
            self._pusher_up().push(
                {"cmd": "bwd", "update": u, "mb": mb,
                 "g": np.asarray(dx)}
            )
        self._note_bwd_done()
        return {"ok": True}

    def _accumulate(self, dp):
        self._grads = dp if self._grads is None \
            else self._acc(self._grads, dp)

    def _note_bwd_done(self):
        self._bwd_done += 1
        self.counters.incr("pipe_microbatches")
        if self._bwd_done == self._m:
            self._ready = True

    def _cmd_finish(self, msg):
        u = int(msg["update"])
        if u <= self._applied:
            return {"ready": True, "applied": self._applied,
                    "bwd_done": self._m}
        return {"ready": self._ready and self._cur_update == u,
                "applied": self._applied, "bwd_done": self._bwd_done}

    def _cmd_commit(self, msg):
        u = int(msg["update"])
        if u <= self._applied:
            return {"applied": self._applied, "loss": self._last_loss}
        if u != self._applied + 1 or not self._ready \
                or self._cur_update != u:
            raise _RestartNeeded(
                f"restart_needed: commit {u} but stage "
                f"{self.proc_index} applied={self._applied} "
                f"ready={self._ready}"
            )
        import jax

        with self.timer.stage("pipe_apply"):
            self._params = jax.tree.map(
                np.asarray,
                self._apply(self._params, self._grads,
                            self.spec["lr"], float(self._m)),
            )
        self._applied = u
        self._last_loss = (self._loss_sum / self._m) if self.is_last \
            else None
        self._cur_update = None
        self._reset_accum()
        self.counters.incr("pipe_updates")
        if self._mgr is not None and self._ckpt_every \
                and u % self._ckpt_every == 0:
            self._mgr.save(u, {"params": self._params})
        return {"applied": self._applied, "loss": self._last_loss}

    def _cmd_rollback(self, msg):
        to = int(msg["to_update"])
        if to != self._applied:
            if to == 0:
                self._params = stage_local_params(
                    build_full_params(self.spec), self.spec,
                    self.proc_index,
                )
            else:
                if self._mgr is None:
                    raise RuntimeError(
                        f"stage {self.proc_index}: rollback to update "
                        f"{to} needs a checkpoint dir"
                    )
                self._params = self._mgr.restore(
                    {"params": self._params}, step=to
                )["params"]
            self._applied = to
            self.counters.incr("pipe_rollbacks")
        self._cur_update = None
        self._reset_accum()
        return {"applied": self._applied}

    def _cmd_get_params(self, msg):
        import jax

        return {"params": jax.tree.map(np.asarray, self._params),
                "applied": self._applied}

    # -- serve loop ----------------------------------------------------------

    def serve_forever(self, stop_event=None, poll_ms=20):
        """Serve until ``stop_event``: the REP socket and (when ShmRPC
        is up) the transport doorbell park in one poller, exactly like
        the replay shard; each pass additionally pumps the neighbor
        pushers (ack drain + overdue same-mid resends)."""
        import zmq

        poller = zmq.Poller()
        poller.register(self._sock, zmq.POLLIN)
        if self._shm is not None and self._shm.fd is not None:
            poller.register(self._shm.fd, zmq.POLLIN)
        while stop_event is None or not stop_event.is_set():
            for pusher in (self._down, self._up):
                if pusher is not None:
                    pusher.pump()
            try:
                events = dict(poller.poll(poll_ms))
            except zmq.ZMQError:
                return
            if self._shm is not None:
                self._shm.pump(self._handle_shm)
            if self._sock not in events:
                continue
            try:
                msg, nbytes = wire.recv_message_sized(self._sock)
            except zmq.ZMQError:
                return
            self.counters.incr("pipe_wire_bytes", nbytes)
            reply = shm_rpc.control_reply(self._shm, msg)
            if reply is None:
                reply = self.handle(msg)
            try:
                sent = wire.send_message(self._sock, reply,
                                         raw_buffers=True)
                self.counters.incr("pipe_wire_bytes", sent)
            except zmq.ZMQError:
                return

    def _handle_shm(self, chan, msg):
        reply = self.handle(msg)
        self._shm.send(chan, reply, raw_buffers=True)

    def close(self):
        try:
            self._sock.close(0)
        except Exception:  # noqa: BLE001 - shutdown best-effort
            pass
        if self._shm is not None:
            try:
                self._shm.close(unlink=True)
            except Exception:  # noqa: BLE001
                pass
            self._shm = None
        for pusher in (self._down, self._up):
            if pusher is not None:
                pusher.close()
        self._down = self._up = None


class _RestartNeeded(RuntimeError):
    """A record/command for an update this stage incarnation cannot
    serve (it restored from a checkpoint, or the driver is replaying) —
    the error text starts with ``restart_needed`` so the driver routes
    it into recovery instead of surfacing it."""


# ---------------------------------------------------------------------------
# the async exactly-once record pusher
# ---------------------------------------------------------------------------


class AsyncPusher:
    """Non-blocking exactly-once record pushes over an
    :class:`~blendjax.btt.transport.RpcChannel`.

    ``push`` stamps a BTMID and sends without waiting; ``pump`` drains
    acks (correlated by mid) and re-sends overdue records under the
    SAME mid (``pipe_resends``) — the receiver's reply cache and
    ``(update, mb)`` dedup make a resend after a lost ack free.  A
    resend first notifies the channel's timeout hook so a dead shm peer
    demotes and the retry rides ZMQ to wherever the peer respawned.
    Error acks park in :attr:`errors` for the owner's loop (the driver
    turns them into recovery; a stage ignores them — the driver
    coordinates)."""

    def __init__(self, channel, counters, *, resend_s=2.5, name="push"):
        self.channel = channel
        self.counters = counters
        self.resend_s = float(resend_s)
        self.name = name
        self._out = OrderedDict()  # mid -> [msg, deadline, resends]
        self.errors = []

    @property
    def outstanding(self):
        return len(self._out)

    def push(self, msg):
        mid = wire.stamp_message_id(msg)
        self._out[mid] = [msg, time.monotonic() + self.resend_s, 0]
        self.channel.send_request(msg, raw_buffers=True)
        return mid

    def pump(self, wait_ms=0):
        """Drain every ready ack (waiting at most ``wait_ms`` for the
        first), then re-send overdue records."""
        while self._out:
            if not self.channel.poll_reply(wait_ms):
                break
            wait_ms = 0
            reply = self.channel.recv_reply()
            if reply is None:
                continue
            mid = reply.get(wire.BTMID_KEY)
            ent = self._out.pop(mid, None)
            if ent is None:
                self.counters.incr("stale_replies")
                continue
            if "error" in reply:
                self.errors.append((ent[0], reply["error"]))
        now = time.monotonic()
        for mid, ent in list(self._out.items()):
            if now < ent[1]:
                continue
            if ent[2] == 0:
                self.channel.notify_timeout()
            ent[1] = now + self.resend_s * min(4, 1 + ent[2])
            ent[2] += 1
            self.counters.incr("pipe_resends")
            self.channel.send_request(ent[0], raw_buffers=True)

    def clear(self):
        self._out.clear()
        self.errors = []

    def reset(self):
        self.clear()
        self.channel.reset()

    def close(self):
        self.clear()
        self.channel.close()


# ---------------------------------------------------------------------------
# the driver
# ---------------------------------------------------------------------------


class MpmdTrain:
    """The pipeline driver: feeds microbatches into stage 0 (and
    targets into the last stage), runs the begin/finish/commit update
    protocol, and heals stage deaths by reconcile-rollback-replay.

    ``update(x, targets, num_microbatches)`` returns the mean
    microbatch loss; numerically it matches
    :func:`~blendjax.parallel.pipeline.make_pipeline_train` + SGD on
    the same spec (tests/test_mpmd.py locks it).
    """

    def __init__(self, addresses, spec, *, counters=None, window=None,
                 rpc_timeout_ms=5000, finish_timeout_s=60.0,
                 recover_timeout_s=90.0, max_restarts=4, context=None):
        from blendjax.btt.transport import RpcChannel

        self.spec = normalize_spec(spec)
        self.addresses = list(addresses)
        if len(self.addresses) != self.spec["n_procs"]:
            raise ValueError(
                f"{len(self.addresses)} stage addresses for "
                f"n_procs={self.spec['n_procs']}"
            )
        self.counters = counters if counters is not None else EventCounters()
        self.timer = StageTimer()
        self.window = int(window) if window else \
            default_window(self.spec["n_procs"])
        self.rpc_timeout_ms = int(rpc_timeout_ms)
        self.finish_timeout_s = float(finish_timeout_s)
        self.recover_timeout_s = float(recover_timeout_s)
        self.max_restarts = int(max_restarts)
        self._ctx = context
        self.policy = FaultPolicy()
        self._ctrl = [
            RpcChannel(a, context=context, name=f"pipe-ctl{i}")
            for i, a in enumerate(self.addresses)
        ]
        self._states = [self.policy.new_state(key=i)
                        for i in range(len(self.addresses))]
        self._feed = AsyncPusher(
            RpcChannel(self.addresses[0], context=context,
                       name="pipe-feed"),
            self.counters, name="driver->s0",
        )
        self._tgt_push = self._feed if len(self.addresses) == 1 else \
            AsyncPusher(
                RpcChannel(self.addresses[-1], context=context,
                           name="pipe-tgt"),
                self.counters, name="driver->last",
            )
        self._update_no = 0
        self._incarnations = {}

    @property
    def updates_done(self):
        return self._update_no

    # -- RPC plumbing --------------------------------------------------------

    def _rpc(self, i, cmd, payload=None, *, timeout_ms=None):
        from blendjax.btt.rpc import exactly_once_rpc

        msg = dict(payload or {})
        msg["cmd"] = cmd
        return exactly_once_rpc(
            lambda: self._ctrl[i], msg,
            policy=self.policy, state=self._states[i],
            counters=self.counters,
            wait_ms=(self.rpc_timeout_ms if timeout_ms is None
                     else int(timeout_ms)),
            remote_name=f"pipe stage {i}",
            span_label=f"pipe{i}_rpc", span_cat="pipe_driver",
            rpc_name=f"pipe-stage-{i}:{cmd}",
            exc_factory=lambda text: PipeRpcError(
                f"pipe stage {i} ({self.addresses[i]}): {text}"
            ),
            retryable=(PipeRpcError,),
        )

    def hello_all(self, timeout_s=60.0):
        """Wait until every stage answers ``hello`` (startup barrier);
        tracks incarnations so later respawns are countable."""
        deadline = time.monotonic() + timeout_s
        infos = []
        for i in range(len(self.addresses)):
            infos.append(self._hello_until(i, deadline))
        return infos

    def _hello_until(self, i, deadline):
        while True:
            try:
                r = self._rpc(i, "hello", timeout_ms=1000)
            except (PipeRpcError, RuntimeError):
                if time.monotonic() >= deadline:
                    raise
                self._ctrl[i].reset()
                time.sleep(0.1)
                continue
            prev = self._incarnations.get(i)
            if prev is not None and prev != r["incarnation"]:
                self.counters.incr("pipe_stage_respawns")
                if r.get("restored") is not None:
                    self.counters.incr("pipe_ckpt_restores")
            self._incarnations[i] = r["incarnation"]
            return r

    # -- the update protocol -------------------------------------------------

    def update(self, x, targets, num_microbatches):
        """One pipeline-parallel training update over a full batch.

        ``x``: (B, d_in); ``targets``: the family's target record —
        an array (mse ``y`` / pg is not array-shaped) or a dict of
        (B, ...) arrays.  Both split into ``num_microbatches`` equal
        microbatches (:func:`~blendjax.parallel.pipeline.microbatch`
        raises the actionable shape error on ragged splits).  Returns
        the mean microbatch loss."""
        from blendjax.parallel.pipeline import microbatch

        tgt = targets if isinstance(targets, dict) else {"y": targets}
        m = int(num_microbatches)
        xs = microbatch(np.asarray(x), m)
        tgts = microbatch(
            {k: np.asarray(v) for k, v in tgt.items()}, m
        )
        u = self._update_no + 1
        restart = False
        for attempt in range(self.max_restarts + 1):
            try:
                return self._run_update(u, xs, tgts, m, restart)
            except PipeRestart as exc:
                if attempt == self.max_restarts:
                    raise RuntimeError(
                        f"pipeline update {u} failed after "
                        f"{self.max_restarts} restarts: {exc}"
                    ) from exc
                logger.warning("pipeline update %d restarting: %s",
                               u, exc)
                self.counters.incr("pipe_restarts")
                self._recover(u)
                u = self._update_no + 1
                restart = True

    def _guard(self, exc):
        """Map a stage failure into restart-vs-fatal: transport errors
        and ``restart_needed`` replies both mean the fleet changed under
        the update."""
        if isinstance(exc, PipeRpcError) or \
                "restart_needed" in str(exc):
            raise PipeRestart(str(exc)) from exc
        raise exc

    def _pump_all(self, wait_ms=0):
        self._feed.pump(wait_ms)
        if self._tgt_push is not self._feed:
            self._tgt_push.pump()
        for pusher in (self._feed, self._tgt_push):
            if pusher.errors:
                msg, err = pusher.errors[0]
                pusher.clear()
                if "restart_needed" in err:
                    raise PipeRestart(err)
                raise RuntimeError(
                    f"pipeline record {msg.get('cmd')} "
                    f"(update {msg.get('update')} mb {msg.get('mb')}) "
                    f"failed remotely: {err}"
                )

    def _run_update(self, u, xs, tgts, m, restart):
        last = len(self.addresses) - 1
        for i in range(len(self.addresses)):
            try:
                self._rpc(i, "begin",
                          {"update": u, "m": m, "restart": restart})
            except (PipeRpcError, RuntimeError) as exc:
                self._guard(exc)
        for mb in range(m):
            with self.timer.stage("pipe_feed"):
                parked = False
                while self._feed.outstanding + \
                        (self._tgt_push.outstanding
                         if self._tgt_push is not self._feed else 0) \
                        >= self.window:
                    if not parked:
                        parked = True
                        self.counters.incr("pipe_feed_parks")
                    self._pump_all(wait_ms=5)
                self._feed.push(
                    {"cmd": "fwd", "update": u, "mb": mb, "x": xs[mb]}
                )
                self._tgt_push.push({
                    "cmd": "tgt", "update": u, "mb": mb,
                    "tgt": {k: v[mb] for k, v in tgts.items()},
                })
                self._pump_all()
            self.counters.incr("pipe_microbatches")
        deadline = time.monotonic() + self.finish_timeout_s
        with self.timer.stage("pipe_finish"):
            for i in range(len(self.addresses)):
                while True:
                    try:
                        r = self._rpc(i, "finish", {"update": u})
                    except (PipeRpcError, RuntimeError) as exc:
                        self._guard(exc)
                    if r["ready"]:
                        break
                    if time.monotonic() >= deadline:
                        raise PipeRestart(
                            f"stage {i} never reached grads-ready for "
                            f"update {u} "
                            f"(bwd_done={r.get('bwd_done')}/{m})"
                        )
                    self._pump_all(wait_ms=5)
        loss = None
        for i in range(len(self.addresses)):
            try:
                r = self._rpc(i, "commit", {"update": u})
            except (PipeRpcError, RuntimeError) as exc:
                self._guard(exc)
            if i == last:
                loss = r["loss"]
        # every record of this update was consumed (the finish barrier
        # proved it) — retire any whose ACK is still in flight, so the
        # next update's pump never resends a delivered record into the
        # committed past
        self._feed.clear()
        if self._tgt_push is not self._feed:
            self._tgt_push.clear()
        self._update_no = u
        self.counters.incr("pipe_updates")
        return loss

    def _recover(self, u):
        """Reconcile after a stage death mid-update ``u``: wait out the
        watchdog respawn, roll every stage back to the lowest applied
        boundary, and let the caller replay the update from its held
        microbatches."""
        self._feed.reset()
        if self._tgt_push is not self._feed:
            self._tgt_push.reset()
        for chan in self._ctrl:
            chan.reset()
        deadline = time.monotonic() + self.recover_timeout_s
        applied = {}
        for i in range(len(self.addresses)):
            applied[i] = self._hello_until(i, deadline)["applied"]
        floor = min(applied.values())
        if floor < u - 1:
            raise RuntimeError(
                f"stage restored to update {floor}, below the driver's "
                f"held update {u} — run stages with ckpt_every=1 for "
                "crash-exact resume"
            )
        for i, a in applied.items():
            if a > floor:
                self._rpc(i, "rollback", {"to_update": floor})
                self.counters.incr("pipe_driver_rollbacks")
        self._update_no = floor

    # -- params --------------------------------------------------------------

    def gather_params(self):
        """Reassemble the full model param tree from every stage (the
        learner's actor-sampling / weight-bus / checkpoint mirror)."""
        locals_by_stage = [
            self._rpc(i, "get_params")["params"]
            for i in range(len(self.addresses))
        ]
        return assemble_full_params(locals_by_stage, self.spec)

    def stage_infos(self):
        return [self._rpc(i, "stage_info")
                for i in range(len(self.addresses))]

    def close(self):
        self._feed.close()
        if self._tgt_push is not self._feed:
            self._tgt_push.close()
        for chan in self._ctrl:
            chan.close()


# ---------------------------------------------------------------------------
# in-process stage threads (tests, benchmarks)
# ---------------------------------------------------------------------------


class _LocalStageHandle:
    def __init__(self, stages, threads, stop):
        self.stages = stages
        self.addresses = [s.address for s in stages]
        self._threads = threads
        self._stop = stop

    def close(self):
        self._stop.set()
        for t in self._threads:
            t.join(timeout=5)
        for s in self.stages:
            s.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


def start_stage_threads(spec, *, ckpt_dir=None, ckpt_every=1,
                        work_us=0, counters=None):
    """Serve every stage of ``spec`` from daemon threads in THIS
    process — same wire surface as the process fleet (the numerics
    tests and the benchmark's warm paths run on these)."""
    spec = normalize_spec(spec)
    stages = [
        MpmdStage(
            "tcp://127.0.0.1:*", spec, p,
            ckpt_dir=ckpt_dir, ckpt_every=ckpt_every, work_us=work_us,
            counters=counters,
        )
        for p in range(spec["n_procs"])
    ]
    for p, s in enumerate(stages):
        s.prev_address = stages[p - 1].address if p > 0 else None
        s.next_address = (stages[p + 1].address
                          if p < len(stages) - 1 else None)
    stop = threading.Event()
    threads = []
    for s in stages:
        t = threading.Thread(
            target=s.serve_forever, kwargs={"stop_event": stop},
            daemon=True, name=f"bjx-pipe-stage-{s.proc_index}",
        )
        t.start()
        threads.append(t)
    return _LocalStageHandle(stages, threads, stop)


# ---------------------------------------------------------------------------
# stage processes + launcher surface
# ---------------------------------------------------------------------------


class _StageLaunchInfo:
    """Duck-typed ``launch_info`` so :class:`~blendjax.btt.watchdog.
    FleetWatchdog` supervises stage processes exactly like Blender
    producers and replay shards."""

    def __init__(self, processes, addresses):
        self.processes = processes
        self.addresses = {"PIPE": addresses}


class StageFleet:
    """N pipeline stage *processes* behind one launcher-compatible
    surface (``launch_info`` + ``respawn(idx)``).  The parent allocates
    every stage's address AND its ``/dev/shm`` base prefix up front, so
    teardown and the watchdog respawn path can ``unlink_base``-sweep
    whatever a SIGKILLed stage (and its clients) left behind — the same
    hygiene as :class:`~blendjax.serve.server.ServerProcess`."""

    def __init__(self, spec, *, ckpt_dir=None, ckpt_every=1, work_us=0,
                 python=None, ready_timeout=120.0):
        from blendjax.replay.shard_client import free_port

        self.spec = normalize_spec(spec)
        self.ckpt_dir = ckpt_dir
        self.ckpt_every = int(ckpt_every)
        self.work_us = int(work_us)
        self.python = python or sys.executable
        self.ready_timeout = float(ready_timeout)
        n = self.spec["n_procs"]
        self.addresses = [f"tcp://127.0.0.1:{free_port()}"
                          for _ in range(n)]
        self.shm_bases = [
            shm_rpc.new_base(f"pst{i}") if shm_rpc.enabled() else None
            for i in range(n)
        ]
        self.launch_info = None

    def _cmd(self, idx):
        n = self.spec["n_procs"]
        cmd = [
            self.python, "-m", "blendjax.parallel.stage",
            "--address", self.addresses[idx],
            "--proc-index", str(idx),
            "--spec", json.dumps(self.spec),
            "--ckpt-every", str(self.ckpt_every),
        ]
        if idx > 0:
            cmd += ["--prev-address", self.addresses[idx - 1]]
        if idx < n - 1:
            cmd += ["--next-address", self.addresses[idx + 1]]
        if self.shm_bases[idx] is not None:
            cmd += ["--shm-base", self.shm_bases[idx]]
        if self.ckpt_dir:
            cmd += ["--ckpt-dir", self.ckpt_dir]
        if self.work_us:
            cmd += ["--work-us", str(self.work_us)]
        return cmd

    def _spawn(self, idx):
        from blendjax.btt.launcher import child_env

        env = child_env()
        env.setdefault("JAX_PLATFORMS", "cpu")
        env.pop("PALLAS_AXON_POOL_IPS", None)
        return subprocess.Popen(self._cmd(idx), env=env,
                                start_new_session=True)

    def __enter__(self):
        procs = [self._spawn(i)
                 for i in range(self.spec["n_procs"])]
        self.launch_info = _StageLaunchInfo(procs, list(self.addresses))
        try:
            self.wait_ready(self.ready_timeout)
        except BaseException:
            self.close()
            raise
        return self

    def wait_ready(self, timeout=120.0):
        deadline = time.monotonic() + timeout
        for i, addr in enumerate(self.addresses):
            while True:
                if time.monotonic() >= deadline:
                    raise TimeoutError(
                        f"pipe stage {i} at {addr} not ready within "
                        f"{timeout:.1f}s"
                    )
                if _stage_hello(addr, timeout_ms=500) is not None:
                    break

    def respawn(self, idx):
        """Relaunch stage ``idx`` with its original command line (the
        watchdog's contract).  The dead incarnation's ``/dev/shm``
        objects are swept first — a SIGKILL runs no cleanup."""
        if self.launch_info is None:
            raise RuntimeError("fleet not launched")
        if self.shm_bases[idx] is not None:
            shm_rpc.unlink_base(self.shm_bases[idx])
        proc = self._spawn(idx)
        self.launch_info.processes[idx] = proc
        return proc

    def close(self):
        info = self.launch_info
        if info is None:
            return
        for p in info.processes:
            if p is None:
                continue
            try:
                p.terminate()
            except Exception:  # noqa: BLE001
                pass
        for p in info.processes:
            if p is None:
                continue
            try:
                p.wait(timeout=5)
            except Exception:  # noqa: BLE001
                try:
                    p.kill()
                except Exception:  # noqa: BLE001
                    pass
        for base in self.shm_bases:
            if base is not None:
                shm_rpc.unlink_base(base)
        self.launch_info = None

    def __exit__(self, *exc):
        self.close()
        return False


def _stage_hello(address, timeout_ms=500, context=None):
    """One throwaway hello against a stage (readiness probe); returns
    the reply dict or None on timeout."""
    import zmq

    ctx = context or zmq.Context.instance()
    sock = ctx.socket(zmq.DEALER)
    sock.setsockopt(zmq.LINGER, 0)
    sock.connect(address)
    try:
        msg = {"cmd": "hello"}
        mid = wire.stamp_message_id(msg)
        wire.send_message_dealer(sock, msg)
        deadline = time.monotonic() + timeout_ms / 1000.0
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return None
            if sock.poll(max(1, int(remaining * 1000)), zmq.POLLIN):
                reply = wire.recv_message_dealer(sock)
                if reply.get(wire.BTMID_KEY) == mid:
                    return reply
    finally:
        sock.close(0)
