"""Sequence/context parallelism: ring attention and Ulysses all-to-all.

The reference has no sequence models at all (SURVEY.md §5 "long-context:
absent"), but blendjax treats long-context as first-class: episodes
streamed out of Blender are *sequences* (frames, observations, actions),
and temporal models over long episodes need the sequence dimension sharded
across chips.  Two standard TPU-native schemes, both pure-JAX collectives
over the ICI mesh:

- **Ring attention** (:func:`ring_attention`): every device holds one
  contiguous sequence shard of Q, K and V.  K/V blocks rotate around the
  ring with ``lax.ppermute`` while each device accumulates its queries'
  attention over every block using an online (flash-style) softmax, so
  memory stays O(S/n) per device and the permute overlaps with the block
  matmul.  Exact — not an approximation.
- **Ulysses** (:func:`ulysses_attention`): ``lax.all_to_all`` reshards
  [seq-sharded, all heads] -> [all seq, head-sharded], runs ordinary full
  attention per head group, and reshards back.  Cheaper collectives for
  moderate sequence lengths; requires ``heads % axis_size == 0``.

Both run *inside* ``shard_map`` (the functions take an ``axis_name``);
:func:`make_ring_attention` wraps one up to act on globally-sharded arrays.
Causal masking uses global positions reconstructed from
``lax.axis_index``, so results match single-device attention bit-for-bit
in structure (small float differences only from blockwise accumulation).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P
try:
    from jax import shard_map  # jax >= 0.8
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map

_NEG = -1e30  # finite mask value: keeps the online-softmax nan-free


def _pvary(x, axes):
    """Mark ``x`` device-varying over ``axes`` under shard_map's vma typing
    (no-op on JAX versions without the typing).  Idempotent: axes the
    value already varies over are skipped — zeros_like of a sharded input
    is already varying, and re-casting raises."""
    try:
        vma = jax.typeof(x).vma
        axes = tuple(a for a in axes if a not in vma)
    except (AttributeError, TypeError):
        pass
    if not axes:
        return x
    if hasattr(lax, "pcast"):
        return lax.pcast(x, axes, to="varying")
    if hasattr(lax, "pvary"):
        return lax.pvary(x, axes)
    return x


def full_attention(q, k, v, causal=False, scale=None, q_offset=0, k_offset=0):
    """Plain softmax attention; the single-device reference implementation.

    q: (B, Sq, H, D), k/v: (B, Sk, H, D).  ``*_offset`` give the global
    position of element 0 along the sequence axis (used by the parallel
    schemes for causal masking across shards).
    """
    d = q.shape[-1]
    scale = scale if scale is not None else 1.0 / jnp.sqrt(d).astype(q.dtype)
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if causal:
        qpos = q_offset + jnp.arange(q.shape[1])
        kpos = k_offset + jnp.arange(k.shape[1])
        mask = kpos[None, :] <= qpos[:, None]
        scores = jnp.where(mask[None, None], scores, _NEG)
    probs = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def ring_attention(q, k, v, axis_name, causal=False, scale=None, vary_axes=None):
    """Exact blockwise attention over a ring of sequence shards.

    Call inside ``shard_map``: q/k/v are the *local* shards
    (B, S/n, H, D) of arrays sharded ``P(None, axis_name, None, None)``.
    Returns the local shard of the attention output.
    """
    n = lax.psum(1, axis_name)
    me = lax.axis_index(axis_name)
    b, s_loc, h, d = q.shape
    scale = scale if scale is not None else 1.0 / jnp.sqrt(d).astype(jnp.float32)

    qf = q.astype(jnp.float32)
    # Receive from the next device: after t rotations we hold block (me + t) % n.
    perm = [(j, (j - 1) % n) for j in range(n)]
    qpos = me * s_loc + jnp.arange(s_loc)

    def accumulate(o, m, l, kb, vb, blk):
        scores = jnp.einsum("bqhd,bkhd->bhqk", qf, kb.astype(jnp.float32)) * scale
        if causal:
            kpos = blk * s_loc + jnp.arange(s_loc)
            mask = kpos[None, :] <= qpos[:, None]
            scores = jnp.where(mask[None, None], scores, _NEG)
        m_new = jnp.maximum(m, scores.max(axis=-1))
        p = jnp.exp(scores - m_new[..., None])
        corr = jnp.exp(m - m_new)
        l = l * corr + p.sum(axis=-1)
        pv = jnp.einsum("bhqk,bkhd->bhqd", p, vb.astype(jnp.float32))
        return o * corr[..., None] + pv, m_new, l

    def body(carry, t):
        o, m, l, kb, vb = carry
        kb = lax.ppermute(kb, axis_name, perm)
        vb = lax.ppermute(vb, axis_name, perm)
        o, m, l = accumulate(o, m, l, kb, vb, (me + t) % n)
        return (o, m, l, kb, vb), None

    o0 = jnp.zeros((b, h, s_loc, d), jnp.float32)
    m0 = jnp.full((b, h, s_loc), _NEG, jnp.float32)
    l0 = jnp.zeros((b, h, s_loc), jnp.float32)
    # Constant-initialized carries are "unvarying" under shard_map's vma
    # typing while the loop body makes them device-varying; align the types
    # over every axis the inputs vary over (seq + optional batch axis).
    axes = tuple(vary_axes) if vary_axes else (axis_name,)
    o0, m0, l0 = (_pvary(x, axes) for x in (o0, m0, l0))
    # Own block first (no rotation), then n-1 rotate-and-accumulate steps.
    o, m, l = accumulate(o0, m0, l0, k, v, me)
    (o, _, l, _, _), _ = lax.scan(body, (o, m, l, k, v), jnp.arange(1, n))
    out = o / l[..., None]
    return out.transpose(0, 2, 1, 3).astype(q.dtype)


def ulysses_attention(q, k, v, axis_name, causal=False, scale=None,
                      inner_attn=None):
    """All-to-all (DeepSpeed-Ulysses style) sequence-parallel attention.

    Call inside ``shard_map`` with local shards (B, S/n, H, D); requires
    ``H % n == 0`` (enforced by ``all_to_all``).  Reshards seq->heads,
    attends over the full sequence for the local head group, reshards back.

    ``inner_attn(q, k, v, causal=..., scale=...)`` overrides the
    full-sequence attention — the natural slot for the fused Pallas
    kernel (:func:`blendjax.ops.flash_attention`), since after the
    all-to-all each device holds the COMPLETE sequence for its head
    group and pays the O(S^2) score matrix right here.
    """
    inner = inner_attn or full_attention
    # (B, S/n, H, D) -> (B, S, H/n, D)
    qh = lax.all_to_all(q, axis_name, split_axis=2, concat_axis=1, tiled=True)
    kh = lax.all_to_all(k, axis_name, split_axis=2, concat_axis=1, tiled=True)
    vh = lax.all_to_all(v, axis_name, split_axis=2, concat_axis=1, tiled=True)
    out = inner(qh, kh, vh, causal=causal, scale=scale)
    # back to (B, S/n, H, D)
    return lax.all_to_all(out, axis_name, split_axis=1, concat_axis=2, tiled=True)


def make_ring_attention(
    mesh, seq_axis="seq", causal=False, impl="ring", batch_axis=None,
    head_axis=None, inner_attn=None,
):
    """Wrap :func:`ring_attention` / :func:`ulysses_attention` for global
    arrays sharded ``P(batch_axis, seq_axis, head_axis, None)`` over
    ``mesh``.

    Returns ``attn(q, k, v) -> out`` usable directly under ``jax.jit``.
    ``inner_attn`` (ulysses only) swaps the per-head-group full-sequence
    attention, e.g. for the fused Pallas flash kernel.
    Composes with data parallelism (``batch_axis='data'``) and — ring only
    — with head-sharded tensor parallelism (``head_axis='model'``): each
    device then ring-rotates K/V for its head block, so sequence and
    tensor parallelism stack.  Ulysses repurposes the head axis for its
    all-to-all and cannot also shard it.
    """
    spec = P(batch_axis, seq_axis, head_axis, None)
    if impl == "ring":
        vary = tuple(a for a in (batch_axis, seq_axis, head_axis) if a is not None)
        inner = functools.partial(
            ring_attention, axis_name=seq_axis, causal=causal, vary_axes=vary
        )
    elif impl == "ulysses":
        if head_axis is not None:
            raise ValueError("ulysses uses the head dim for its all-to-all; "
                             "head_axis sharding is ring-only")
        inner = functools.partial(ulysses_attention, axis_name=seq_axis,
                                  causal=causal, inner_attn=inner_attn)
    else:
        raise ValueError(f"unknown impl {impl!r} (want 'ring' or 'ulysses')")
    mapped = shard_map(
        inner, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec
    )

    def attn(q, k, v):
        sh = NamedSharding(mesh, spec)
        q, k, v = (lax.with_sharding_constraint(x, sh) for x in (q, k, v))
        return mapped(q, k, v)

    return attn
