"""Sequence/context parallelism: ring attention and Ulysses all-to-all.

The reference has no sequence models at all (SURVEY.md §5 "long-context:
absent"), but blendjax treats long-context as first-class: episodes
streamed out of Blender are *sequences* (frames, observations, actions),
and temporal models over long episodes need the sequence dimension sharded
across chips.  Four TPU-native schemes, all pure-JAX collectives (plus
the Pallas kernel) over the ICI mesh:

- **Ring attention** (:func:`ring_attention`): every device holds one
  contiguous sequence shard of Q, K and V.  K/V blocks rotate around the
  ring with ``lax.ppermute`` while each device accumulates its queries'
  attention over every block using an online (flash-style) softmax, so
  memory stays O(S/n) per device and the permute overlaps with the block
  matmul.  Exact — not an approximation.
- **Ring + flash** (:func:`ring_flash_attention`): the same ring, with
  the fused Pallas flash kernel as the per-block-pair attention — no
  (S/n, S/n) score matrix materializes even within a block, and
  differentiation is a ring-level custom VJP whose backward rotates K/V
  *and* their gradient accumulators (fused dQ and dK/dV kernels per
  visible pair).  The long-context configuration: ring scales past
  Ulysses' ``heads % n`` constraint while keeping flash's O(block)
  memory.
- **Zigzag ring + flash** (:func:`zigzag_flash_attention`): ring+flash
  with the load-balanced chunk layout for CAUSAL attention — plain
  causal ring leaves early devices idle (device 0's queries see one
  block, device n-1's see all n); pairing chunks from both sequence
  ends (shard d holds chunks d and 2n-1-d) gives every device identical
  visible work per rotation.
- **Ulysses** (:func:`ulysses_attention`): ``lax.all_to_all`` reshards
  [seq-sharded, all heads] -> [all seq, head-sharded], runs ordinary full
  attention per head group, and reshards back.  Cheaper collectives for
  moderate sequence lengths; requires ``heads % axis_size == 0``
  (``inner_attn`` slots the flash kernel in per head group).

All run *inside* ``shard_map`` (the functions take an ``axis_name``);
:func:`make_ring_attention` wraps one up to act on globally-sharded arrays.
Causal masking uses global positions reconstructed from
``lax.axis_index``, so results match single-device attention bit-for-bit
in structure (small float differences only from blockwise accumulation).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P
try:
    from jax import shard_map  # jax >= 0.8
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map

_NEG = -1e30  # finite mask value: keeps the online-softmax nan-free


def _pvary(x, axes):
    """Mark ``x`` device-varying over ``axes`` under shard_map's vma typing
    (no-op on JAX versions without the typing).  Idempotent: axes the
    value already varies over are skipped — zeros_like of a sharded input
    is already varying, and re-casting raises."""
    try:
        vma = jax.typeof(x).vma
        axes = tuple(a for a in axes if a not in vma)
    except (AttributeError, TypeError):
        pass
    if not axes:
        return x
    if hasattr(lax, "pcast"):
        return lax.pcast(x, axes, to="varying")
    if hasattr(lax, "pvary"):
        return lax.pvary(x, axes)
    return x


def full_attention(q, k, v, causal=False, scale=None, q_offset=0, k_offset=0,
                   window=None):
    """Plain softmax attention; the single-device reference implementation.

    q: (B, Sq, H, D), k/v: (B, Sk, H, D).  ``*_offset`` give the global
    position of element 0 along the sequence axis (used by the parallel
    schemes for causal masking across shards).  ``window=W`` (causal
    only) is sliding-window attention: query i sees keys in
    ``(i - W, i]`` — the reference semantics for
    ``blendjax.ops.flash_attention``'s windowed kernel.  k/v with fewer
    heads than q (GQA) are broadcast per group — the reference
    semantics for the kernel's grouped KV head mapping.
    """
    if window is not None:
        if not causal:
            raise ValueError("window requires causal=True")
        if window < 1:
            raise ValueError(f"window must be >= 1, got {window}")
    if k.shape[2] != q.shape[2]:
        if q.shape[2] % k.shape[2]:
            raise ValueError(
                f"q heads {q.shape[2]} must be a multiple of kv heads "
                f"{k.shape[2]}"
            )
        rep = q.shape[2] // k.shape[2]
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    d = q.shape[-1]
    scale = scale if scale is not None else 1.0 / jnp.sqrt(d).astype(q.dtype)
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if causal:
        qpos = q_offset + jnp.arange(q.shape[1])
        kpos = k_offset + jnp.arange(k.shape[1])
        mask = kpos[None, :] <= qpos[:, None]
        if window is not None:
            mask &= kpos[None, :] > qpos[:, None] - window
        scores = jnp.where(mask[None, None], scores, _NEG)
    probs = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def _window_ring_deltas(window, s_loc, n):
    """How many earlier neighbor shards a sliding window reaches: shard
    me needs shard me-d iff the newest key there, position
    ``(me-d+1)*s_loc - 1``, is within ``window`` of me's oldest query
    ``me*s_loc`` — i.e. ``(d-1)*s_loc + 2 <= window``.  This is the
    windowed ring's whole point: compute AND ring traffic become
    O(window), not O(S) — a ring step rotates only ``dmax`` times."""
    if window < 2:
        return 0
    return min(n - 1, (window - 2) // s_loc + 1)


def ring_attention(q, k, v, axis_name, causal=False, scale=None,
                   vary_axes=None, window=None):
    """Exact blockwise attention over a ring of sequence shards.

    Call inside ``shard_map``: q/k/v are the *local* shards
    (B, S/n, H, D) of arrays sharded ``P(None, axis_name, None, None)``.
    Returns the local shard of the attention output.

    ``window=W`` (causal only) is sliding-window attention: the ring
    then rotates BACKWARD and stops after ``_window_ring_deltas`` steps
    — shards older than the window are never fetched, so ring traffic
    scales with the window, not the sequence.
    """
    if window is not None and not causal:
        raise ValueError("window requires causal=True")
    if k.shape[2] != q.shape[2]:
        raise ValueError(
            "ring does not support GQA (kv heads != q heads); "
            "use impl='ulysses' or repeat kv heads before the ring"
        )
    n = lax.psum(1, axis_name)
    me = lax.axis_index(axis_name)
    b, s_loc, h, d = q.shape
    scale = scale if scale is not None else 1.0 / jnp.sqrt(d).astype(jnp.float32)

    qf = q.astype(jnp.float32)
    # Receive from the next device: after t rotations we hold block (me + t) % n.
    perm = [(j, (j - 1) % n) for j in range(n)]
    qpos = me * s_loc + jnp.arange(s_loc)

    def accumulate(o, m, l, kb, vb, blk):
        scores = jnp.einsum("bqhd,bkhd->bhqk", qf, kb.astype(jnp.float32)) * scale
        if causal:
            kpos = blk * s_loc + jnp.arange(s_loc)
            mask = kpos[None, :] <= qpos[:, None]
            if window is not None:
                mask &= kpos[None, :] > qpos[:, None] - window
            scores = jnp.where(mask[None, None], scores, _NEG)
        m_new = jnp.maximum(m, scores.max(axis=-1))
        p = jnp.exp(scores - m_new[..., None])
        corr = jnp.exp(m - m_new)
        l = l * corr + p.sum(axis=-1)
        pv = jnp.einsum("bhqk,bkhd->bhqd", p, vb.astype(jnp.float32))
        return o * corr[..., None] + pv, m_new, l

    o0 = jnp.zeros((b, h, s_loc, d), jnp.float32)
    m0 = jnp.full((b, h, s_loc), _NEG, jnp.float32)
    l0 = jnp.zeros((b, h, s_loc), jnp.float32)
    # Constant-initialized carries are "unvarying" under shard_map's vma
    # typing while the loop body makes them device-varying; align the types
    # over every axis the inputs vary over (seq + optional batch axis).
    axes = tuple(vary_axes) if vary_axes else (axis_name,)
    o0, m0, l0 = (_pvary(x, axes) for x in (o0, m0, l0))
    # Own block first (no rotation): every query sees itself (window >= 1),
    # so m is finite before any possibly-all-masked rotation pair — an
    # all-masked pair then contributes exp(_NEG - m) = 0, not garbage.
    o, m, l = accumulate(o0, m0, l0, k, v, me)

    if window is None:
        def body(carry, t):
            o, m, l, kb, vb = carry
            kb = lax.ppermute(kb, axis_name, perm)
            vb = lax.ppermute(vb, axis_name, perm)
            o, m, l = accumulate(o, m, l, kb, vb, (me + t) % n)
            return (o, m, l, kb, vb), None

        (o, _, l, _, _), _ = lax.scan(body, (o, m, l, k, v), jnp.arange(1, n))
    else:
        # windowed: rotate BACKWARD (earlier shards) and stop once the
        # window is exhausted — t rotations put shard (me - t) % n here
        perm_back = [(j, (j + 1) % n) for j in range(n)]
        dmax = _window_ring_deltas(window, s_loc, n)

        def body(carry, t):
            o, m, l, kb, vb = carry
            kb = lax.ppermute(kb, axis_name, perm_back)
            vb = lax.ppermute(vb, axis_name, perm_back)
            # (me - t) % n wraps to a FUTURE shard on devices me < t;
            # its columns fail the causal mask, so the all-masked pair
            # is a (wasted but exact) no-op on those devices
            o, m, l = accumulate(o, m, l, kb, vb, (me - t) % n)
            return (o, m, l, kb, vb), None

        if dmax > 0:
            (o, _, l, _, _), _ = lax.scan(
                body, (o, m, l, k, v), jnp.arange(1, dmax + 1)
            )
    out = o / l[..., None]
    return out.transpose(0, 2, 1, 3).astype(q.dtype)


def _ring_blk(s_loc):
    """Flash tile for a local shard — the shared policy from
    :func:`blendjax.ops.flash_attention.flash_block_size`."""
    from blendjax.ops.flash_attention import flash_block_size

    return flash_block_size(s_loc)


def _lse_combine(o, lse, o_b, lse_b):
    """Merge a new normalized partial (o_b, lse_b) into a running
    (o, lse) by logsumexp reweighting — the online-softmax recurrence at
    ring granularity, shared by the ring_flash and zigzag variants.
    ``o``: (B, S, H, D) f32; ``lse``: (B, H, S) f32."""
    lse_new = jnp.logaddexp(lse, lse_b)
    w_old = jnp.exp(lse - lse_new).transpose(0, 2, 1)[..., None]
    w_new = jnp.exp(lse_b - lse_new).transpose(0, 2, 1)[..., None]
    return o * w_old + o_b * w_new, lse_new


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def ring_flash_attention(q, k, v, axis_name, causal=False, scale=None,
                         interpret=False, vary_axes=None, window=None):
    """:func:`ring_attention` with the fused Pallas flash kernel per
    block pair — O(S/n) memory per device AND no (S/n, S/n) score matrix
    materialized within a block.

    Call inside ``shard_map`` with local shards (B, S/n, H, D).  Each
    ring step runs the flash kernel on (my queries x held KV block):
    blocks strictly before mine attend unmasked, my own block attends
    causally, later blocks are skipped entirely (their probabilities are
    exactly zero); partial outputs combine across blocks by logsumexp
    reweighting — the same online-softmax recurrence the kernel runs
    internally, lifted to ring granularity.  Differentiation is a
    custom VJP at the ring level: the backward rotates K/V *and* their
    gradient accumulators around the ring, running the fused dQ and
    dK/dV kernels per visible pair, so no pass materializes scores.

    ``window=W`` (causal only) is sliding-window attention: the ring
    rotates BACKWARD and stops after ``_window_ring_deltas(W, S/n, n)``
    steps, each pair running the windowed kernel with a STATIC
    ``q_offset`` (the rotation count is a Python loop index, so every
    pair's row/col offset is known at trace time) — per-device compute,
    HBM traffic, AND ring collectives all scale O(W) instead of O(S).
    A window wider than the sequence degrades gracefully to the full
    causal ring.
    """
    out, _ = _ring_flash_fwd(
        q, k, v, axis_name, causal, scale, interpret, vary_axes, window
    )
    return out


def _ring_flash_fwd(q, k, v, axis_name, causal, scale, interpret,
                    vary_axes, window=None):
    from blendjax.ops.flash_attention import _default_scale, _flash_fwd_impl

    if k.shape[2] != q.shape[2]:
        # the kernel itself handles GQA, but the ring-level custom VJP
        # rotates per-q-head gradient accumulators — threading the head
        # map through it is not implemented.  Raise here rather than let
        # the forward silently succeed and the backward emit mis-shaped
        # cotangents (use ulysses, or repeat kv heads upstream)
        raise ValueError(
            "ring_flash does not support GQA (kv heads != q heads); "
            "use impl='ulysses' or repeat kv heads before the ring"
        )
    if window is not None:
        if not causal:
            raise ValueError("window requires causal=True")
        return _ring_flash_fwd_windowed(
            q, k, v, axis_name, scale, interpret, vary_axes, window
        )

    n = lax.psum(1, axis_name)
    me = lax.axis_index(axis_name)
    b, s_loc, h, d = q.shape
    scale_v = _default_scale(scale, d)
    blk = _ring_blk(s_loc)
    perm = [(j, (j - 1) % n) for j in range(n)]

    def pair(kb, vb, diag):
        # out_dtype=f32: the kernel's internal accumulator is f32 —
        # emitting f32 partials keeps the cross-block combination free
        # of per-block rounding (bf16 inputs still feed the MXU as bf16)
        o_b, res = _flash_fwd_impl(
            q, kb, vb, diag, scale_v, blk, blk, interpret,
            out_dtype=jnp.float32,
        )
        lse_b = res[4].reshape(b, h, s_loc)
        return o_b, lse_b

    combine = _lse_combine

    def step_compute(o, lse, kb, vb, blk_idx):
        if not causal:
            return combine(o, lse, *pair(kb, vb, False))
        # 0: later block (skip — all-masked), 1: earlier (full), 2: own
        # (causal diagonal).  The kernel must NOT run on an all-masked
        # pair: its online softmax would renormalize over masked columns.
        mode = jnp.where(blk_idx > me, 0, jnp.where(blk_idx < me, 1, 2))
        return lax.switch(
            mode,
            [
                lambda: (o, lse),
                lambda: combine(o, lse, *pair(kb, vb, False)),
                lambda: combine(o, lse, *pair(kb, vb, True)),
            ],
        )

    def body(carry, t):
        o, lse, kb, vb = carry
        kb = lax.ppermute(kb, axis_name, perm)
        vb = lax.ppermute(vb, axis_name, perm)
        o, lse = step_compute(o, lse, kb, vb, (me + t) % n)
        return (o, lse, kb, vb), None

    o0 = jnp.zeros((b, s_loc, h, d), jnp.float32)
    lse0 = jnp.full((b, h, s_loc), _NEG, jnp.float32)
    axes = tuple(vary_axes) if vary_axes else (axis_name,)
    o0, lse0 = (_pvary(x, axes) for x in (o0, lse0))
    o, lse = step_compute(o0, lse0, k, v, me)  # own block, no rotation
    (o, lse, _, _), _ = lax.scan(body, (o, lse, k, v), jnp.arange(1, n))
    out = o.astype(q.dtype)
    return out, (q, k, v, out, lse)


def _ring_flash_fwd_windowed(q, k, v, axis_name, scale, interpret,
                             vary_axes, window):
    """Sliding-window ring + flash forward.

    Rotation ``t`` (a PYTHON loop index — ``dmax`` is static) holds
    shard ``(me - t) % n``: an earlier shard at static offset
    ``t * s_loc`` for devices ``me >= t``, a wrapped future shard
    otherwise.  The pair kernel runs with ``causal=True, window,
    q_offset=t*s_loc`` — at that offset the causal mask is all-true and
    the window mask prunes — under ``lax.cond`` so wrapped devices skip
    the compute entirely (the ppermute itself is unconditional: it is a
    collective).  Rows beyond a pair's window emit ``lse = -1e30`` and
    weigh zero in the logsumexp combine."""
    from blendjax.ops.flash_attention import _default_scale, _flash_fwd_impl

    n = lax.psum(1, axis_name)
    me = lax.axis_index(axis_name)
    b, s_loc, h, d = q.shape
    scale_v = _default_scale(scale, d)
    blk = _ring_blk(s_loc)
    perm_back = [(j, (j + 1) % n) for j in range(n)]
    dmax = _window_ring_deltas(window, s_loc, n)

    def pair(kb, vb, q_offset):
        o_b, res = _flash_fwd_impl(
            q, kb, vb, True, scale_v, blk, blk, interpret,
            out_dtype=jnp.float32, window=window, q_offset=q_offset,
        )
        return o_b, res[4].reshape(b, h, s_loc)

    # own shard: every query sees itself, so (o, lse) start finite
    o, lse = pair(k, v, 0)
    kb, vb = k, v
    for t in range(1, dmax + 1):
        kb = lax.ppermute(kb, axis_name, perm_back)
        vb = lax.ppermute(vb, axis_name, perm_back)
        o, lse = lax.cond(
            me >= t,
            lambda kb=kb, vb=vb, o=o, lse=lse, t=t: _lse_combine(
                o, lse, *pair(kb, vb, t * s_loc)
            ),
            lambda o=o, lse=lse: (o, lse),
        )
    out = o.astype(q.dtype)
    return out, (q, k, v, out, lse)


def _ring_flash_bwd_windowed(axis_name, scale, interpret, window, res, g):
    """Backward of the windowed ring: dK/dV accumulators TRAVEL with
    their shard for the ``dmax`` rotations (each visiting device adds
    its pair's contribution), then a single ``ppermute`` jumps every
    accumulator straight home — ``dmax + 1`` collectives per gradient
    array instead of the full ring's ``n``.

    Takes no ``vary_axes`` (unlike the forwards): every accumulator is
    seeded from ``pair_grads`` outputs, which are already device-varying
    (they consume the per-device ``q``/``k``/``v`` shards), so no
    ``_pvary`` seeding is needed — a zeros-init refactor would reintroduce
    the shard_map varying-axis mismatch and must re-thread ``vary_axes``
    through here."""
    from blendjax.ops.flash_attention import (
        _default_scale,
        _dkv_pass,
        _dq_pass,
        _flat,
        _unflat,
    )

    q, k, v, out, lse = res
    n = lax.psum(1, axis_name)
    me = lax.axis_index(axis_name)
    b, s_loc, h, d = q.shape
    scale_v = _default_scale(scale, d)
    blk = _ring_blk(s_loc)
    perm_back = [(j, (j + 1) % n) for j in range(n)]
    dmax = _window_ring_deltas(window, s_loc, n)

    qf, dof, of = _flat(q), _flat(g), _flat(out)
    delta = (dof.astype(jnp.float32) * of.astype(jnp.float32)).sum(
        -1, keepdims=True
    )
    lse_f = lse.reshape(b * h, s_loc, 1)

    def pair_grads(kbf, vbf, q_offset):
        dq_c = _dq_pass(qf, kbf, vbf, dof, lse_f, delta, True, scale_v,
                        blk, blk, interpret, out_dtype=jnp.float32,
                        window=window, q_offset=q_offset)
        dk_c, dv_c = _dkv_pass(qf, kbf, vbf, dof, lse_f, delta, True,
                               scale_v, blk, blk, interpret,
                               out_dtype=jnp.float32, window=window,
                               q_offset=q_offset)
        return dq_c, dk_c, dv_c

    # own pair seeds both the local dQ and the traveling dK/dV
    dq, dk_t, dv_t = pair_grads(_flat(k), _flat(v), 0)
    kbf, vbf = _flat(k), _flat(v)
    for t in range(1, dmax + 1):
        kbf = lax.ppermute(kbf, axis_name, perm_back)
        vbf = lax.ppermute(vbf, axis_name, perm_back)
        dk_t = lax.ppermute(dk_t, axis_name, perm_back)
        dv_t = lax.ppermute(dv_t, axis_name, perm_back)
        dq, dk_t, dv_t = lax.cond(
            me >= t,
            lambda kbf=kbf, vbf=vbf, dq=dq, dk_t=dk_t, dv_t=dv_t, t=t: (
                lambda c: (dq + c[0], dk_t + c[1], dv_t + c[2])
            )(pair_grads(kbf, vbf, t * s_loc)),
            lambda dq=dq, dk_t=dk_t, dv_t=dv_t: (dq, dk_t, dv_t),
        )
    if dmax > 0:
        # one jump home: the accumulator traveling with shard
        # (me - dmax) % n returns to its owner
        perm_home = [(j, (j - dmax) % n) for j in range(n)]
        dk_t = lax.ppermute(dk_t, axis_name, perm_home)
        dv_t = lax.ppermute(dv_t, axis_name, perm_home)
    return (
        _unflat(dq, b, h).astype(q.dtype),
        _unflat(dk_t, b, h).astype(k.dtype),
        _unflat(dv_t, b, h).astype(v.dtype),
    )


def _ring_flash_bwd(axis_name, causal, scale, interpret, vary_axes,
                    window, res, g):
    if window is not None:
        return _ring_flash_bwd_windowed(
            axis_name, scale, interpret, window, res, g
        )
    from blendjax.ops.flash_attention import (
        _default_scale,
        _dkv_pass,
        _dq_pass,
        _flat,
        _unflat,
    )

    q, k, v, out, lse = res
    n = lax.psum(1, axis_name)
    me = lax.axis_index(axis_name)
    b, s_loc, h, d = q.shape
    scale_v = _default_scale(scale, d)
    blk = _ring_blk(s_loc)
    perm = [(j, (j - 1) % n) for j in range(n)]

    qf, dof, of = _flat(q), _flat(g), _flat(out)
    delta = (dof.astype(jnp.float32) * of.astype(jnp.float32)).sum(
        -1, keepdims=True
    )
    lse_f = lse.reshape(b * h, s_loc, 1)

    def pair_grads(kbf, vbf, diag):
        # out_dtype=f32: per-pair gradients leave the kernels unrounded
        # so the n-block accumulation never sums bf16-rounded partials
        dq_c = _dq_pass(qf, kbf, vbf, dof, lse_f, delta, diag, scale_v,
                        blk, blk, interpret, out_dtype=jnp.float32)
        dk_c, dv_c = _dkv_pass(qf, kbf, vbf, dof, lse_f, delta, diag,
                               scale_v, blk, blk, interpret,
                               out_dtype=jnp.float32)
        return dq_c, dk_c, dv_c

    def step_compute(dq, dk, dv, kbf, vbf, blk_idx):
        if not causal:
            dq_c, dk_c, dv_c = pair_grads(kbf, vbf, False)
            return dq + dq_c, dk + dk_c, dv + dv_c

        def visible(diag):
            dq_c, dk_c, dv_c = pair_grads(kbf, vbf, diag)
            return dq + dq_c, dk + dk_c, dv + dv_c

        mode = jnp.where(blk_idx > me, 0, jnp.where(blk_idx < me, 1, 2))
        return lax.switch(
            mode,
            [
                lambda: (dq, dk, dv),
                lambda: visible(False),
                lambda: visible(True),
            ],
        )

    def body(carry, t):
        # held block's dK/dV accumulators travel WITH the block: after
        # the full cycle of n rotations each lands back on its owner
        dq, dk, dv, kbf, vbf = carry
        dq, dk, dv = step_compute(dq, dk, dv, kbf, vbf, (me + t) % n)
        kbf = lax.ppermute(kbf, axis_name, perm)
        vbf = lax.ppermute(vbf, axis_name, perm)
        dk = lax.ppermute(dk, axis_name, perm)
        dv = lax.ppermute(dv, axis_name, perm)
        return (dq, dk, dv, kbf, vbf), None

    axes = tuple(vary_axes) if vary_axes else (axis_name,)
    dq0, dk0, dv0 = (
        _pvary(jnp.zeros((b * h, s_loc, d), jnp.float32), axes)
        for _ in range(3)
    )
    (dq, dk, dv, kbf, vbf), _ = lax.scan(
        body, (dq0, dk0, dv0, _flat(k), _flat(v)), jnp.arange(n - 1)
    )
    # final block: compute, then rotate ONLY the accumulators home — the
    # K/V blocks are done, and their last ppermute would be wasted ring
    # traffic on every training step's critical path
    dq, dk, dv = step_compute(dq, dk, dv, kbf, vbf, (me + (n - 1)) % n)
    dk = lax.ppermute(dk, axis_name, perm)
    dv = lax.ppermute(dv, axis_name, perm)
    return (
        _unflat(dq, b, h).astype(q.dtype),
        _unflat(dk, b, h).astype(k.dtype),
        _unflat(dv, b, h).astype(v.dtype),
    )


ring_flash_attention.defvjp(_ring_flash_fwd, _ring_flash_bwd)


def _zigzag_perm(seq_len, n):
    """Global index permutation laying the sequence out so contiguous
    shard ``d`` holds chunks ``(d, 2n-1-d)`` of ``2n`` contiguous
    chunks.  Numpy (static): the permutation is data-independent."""
    import numpy as _np

    c = 2 * n
    if seq_len % c:
        raise ValueError(
            f"zigzag layout needs sequence length {seq_len} divisible "
            f"by 2*n_devices = {c}"
        )
    chunk = seq_len // c
    order = []
    for dd in range(n):
        order += [dd, c - 1 - dd]
    return _np.concatenate(
        [_np.arange(o * chunk, (o + 1) * chunk) for o in order]
    )


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def zigzag_flash_attention(q, k, v, axis_name, scale=None,
                           interpret=False, vary_axes=None):
    """Load-balanced CAUSAL ring attention with the fused flash kernel.

    Plain causal ring attention is imbalanced: device 0's queries see one
    block, device n-1's see all n — the ring's total compute slots are
    ~2x the visible work, and every step waits for the busiest device.
    The zigzag layout pairs chunks from both ends of the sequence
    (shard ``d`` holds chunks ``d`` and ``2n-1-d`` of ``2n``), making
    every device's total visible work identical (``2n+1`` chunk pairs).

    Call inside ``shard_map`` with local shards ALREADY in zigzag layout
    (:func:`make_ring_attention` with ``impl='zigzag_flash'`` applies
    the global permutation and its inverse around the shard_map).  Each
    ring step runs up to 4 flash-kernel pair calls (2 query half-chunks
    x 2 held KV half-chunks), each unmasked / causal-diagonal / skipped
    by chunk-index comparison; the backward rotates KV *and* per-half
    dK/dV accumulators like :func:`ring_flash_attention`.  Causal only —
    non-causal rings have no imbalance to fix.
    """
    out, _ = _zz_fwd(q, k, v, axis_name, scale, interpret, vary_axes)
    return out


def _zz_fwd(q, k, v, axis_name, scale, interpret, vary_axes):
    from blendjax.ops.flash_attention import _default_scale, _flash_fwd_impl

    if k.shape[2] != q.shape[2]:
        # same limitation as ring_flash: the ring-level VJP rotates
        # per-q-head accumulators (see _ring_flash_fwd)
        raise ValueError(
            "zigzag_flash does not support GQA (kv heads != q heads); "
            "use impl='ulysses' or repeat kv heads before the ring"
        )
    n = lax.psum(1, axis_name)
    me = lax.axis_index(axis_name)
    b, s_loc, h, d = q.shape
    half = s_loc // 2
    c = 2 * n
    scale_v = _default_scale(scale, d)
    blk = _ring_blk(half)
    perm = [(j, (j - 1) % n) for j in range(n)]

    q_halves = (q[:, :half], q[:, half:])
    q_idx = (me, c - 1 - me)  # chunk indices of my query halves

    def pair(qh, kh, vh, diag):
        o_b, res = _flash_fwd_impl(
            qh, kh, vh, diag, scale_v, blk, blk, interpret,
            out_dtype=jnp.float32,
        )
        return o_b, res[4].reshape(b, h, half)

    def half_step(acc, qh, qi, kh, vh, ki):
        o, lse = acc
        mode = jnp.where(ki > qi, 0, jnp.where(ki < qi, 1, 2))
        return lax.switch(
            mode,
            [
                lambda: (o, lse),
                lambda: _lse_combine(o, lse, *pair(qh, kh, vh, False)),
                lambda: _lse_combine(o, lse, *pair(qh, kh, vh, True)),
            ],
        )

    def step_compute(accs, kb, vb, src):
        k_halves = (kb[:, :half], kb[:, half:])
        v_halves = (vb[:, :half], vb[:, half:])
        k_idx = (src, c - 1 - src)
        out_accs = []
        for qh, qi, acc in zip(q_halves, q_idx, accs):
            for kh, vh, ki in zip(k_halves, v_halves, k_idx):
                acc = half_step(acc, qh, qi, kh, vh, ki)
            out_accs.append(acc)
        return tuple(out_accs)

    def body(carry, t):
        accs, kb, vb = carry
        kb = lax.ppermute(kb, axis_name, perm)
        vb = lax.ppermute(vb, axis_name, perm)
        accs = step_compute(accs, kb, vb, (me + t) % n)
        return (accs, kb, vb), None

    axes = tuple(vary_axes) if vary_axes else (axis_name,)
    accs0 = tuple(
        (
            _pvary(jnp.zeros((b, half, h, d), jnp.float32), axes),
            _pvary(jnp.full((b, h, half), _NEG, jnp.float32), axes),
        )
        for _ in range(2)
    )
    accs = step_compute(accs0, k, v, me)  # own pair, no rotation
    (accs, _, _), _ = lax.scan(body, (accs, k, v), jnp.arange(1, n))
    (oa, lse_a), (ob, lse_b) = accs
    out = jnp.concatenate([oa, ob], axis=1).astype(q.dtype)
    lse = jnp.concatenate([lse_a, lse_b], axis=2)
    return out, (q, k, v, out, lse)


def _zz_bwd(axis_name, scale, interpret, vary_axes, res, g):
    from blendjax.ops.flash_attention import (
        _default_scale,
        _dkv_pass,
        _dq_pass,
        _flat,
        _unflat,
    )

    q, k, v, out, lse = res
    n = lax.psum(1, axis_name)
    me = lax.axis_index(axis_name)
    b, s_loc, h, d = q.shape
    half = s_loc // 2
    c = 2 * n
    scale_v = _default_scale(scale, d)
    blk = _ring_blk(half)
    perm = [(j, (j - 1) % n) for j in range(n)]

    def half_flat(x, i):  # (b, s_loc, h, d) -> flat (bh, half, d) half i
        return _flat(x[:, i * half:(i + 1) * half])

    qf_h = (half_flat(q, 0), half_flat(q, 1))
    dof_h = (half_flat(g, 0), half_flat(g, 1))
    of_h = (half_flat(out, 0), half_flat(out, 1))
    delta_h = tuple(
        (do.astype(jnp.float32) * o.astype(jnp.float32)).sum(
            -1, keepdims=True
        )
        for do, o in zip(dof_h, of_h)
    )
    lse_h = (
        lse[:, :, :half].reshape(b * h, half, 1),
        lse[:, :, half:].reshape(b * h, half, 1),
    )
    q_idx = (me, c - 1 - me)

    def pair_grads(qi_f, kf, vf, dof, lse_f, delta, diag):
        dq_c = _dq_pass(qi_f, kf, vf, dof, lse_f, delta, diag, scale_v,
                        blk, blk, interpret, out_dtype=jnp.float32)
        dk_c, dv_c = _dkv_pass(qi_f, kf, vf, dof, lse_f, delta, diag,
                               scale_v, blk, blk, interpret,
                               out_dtype=jnp.float32)
        return dq_c, dk_c, dv_c

    def step_compute(dqs, dks, dvs, kbf_h, vbf_h, src):
        k_idx = (src, c - 1 - src)
        dqs, dks, dvs = list(dqs), list(dks), list(dvs)
        for a, qi in enumerate(q_idx):
            for kk, ki in enumerate(k_idx):

                def visible(diag, a=a, kk=kk):
                    dq_c, dk_c, dv_c = pair_grads(
                        qf_h[a], kbf_h[kk], vbf_h[kk], dof_h[a],
                        lse_h[a], delta_h[a], diag,
                    )
                    return dqs[a] + dq_c, dks[kk] + dk_c, dvs[kk] + dv_c

                mode = jnp.where(ki > qi, 0, jnp.where(ki < qi, 1, 2))
                dqs[a], dks[kk], dvs[kk] = lax.switch(
                    mode,
                    [
                        lambda a=a, kk=kk: (dqs[a], dks[kk], dvs[kk]),
                        lambda: visible(False),
                        lambda: visible(True),
                    ],
                )
        return tuple(dqs), tuple(dks), tuple(dvs)

    def body(carry, t):
        dqs, dks, dvs, kbf_h, vbf_h = carry
        dqs, dks, dvs = step_compute(dqs, dks, dvs, kbf_h, vbf_h,
                                     (me + t) % n)
        kbf_h = tuple(lax.ppermute(x, axis_name, perm) for x in kbf_h)
        vbf_h = tuple(lax.ppermute(x, axis_name, perm) for x in vbf_h)
        dks = tuple(lax.ppermute(x, axis_name, perm) for x in dks)
        dvs = tuple(lax.ppermute(x, axis_name, perm) for x in dvs)
        return (dqs, dks, dvs, kbf_h, vbf_h), None

    axes = tuple(vary_axes) if vary_axes else (axis_name,)

    def zeros2():
        return tuple(
            _pvary(jnp.zeros((b * h, half, d), jnp.float32), axes)
            for _ in range(2)
        )

    kbf_h = (half_flat(k, 0), half_flat(k, 1))
    vbf_h = (half_flat(v, 0), half_flat(v, 1))
    carry = (zeros2(), zeros2(), zeros2(), kbf_h, vbf_h)
    (dqs, dks, dvs, kbf_h, vbf_h), _ = lax.scan(
        body, carry, jnp.arange(n - 1)
    )
    # final pair: compute, then rotate ONLY the dK/dV accumulators home
    dqs, dks, dvs = step_compute(dqs, dks, dvs, kbf_h, vbf_h,
                                 (me + (n - 1)) % n)
    dks = tuple(lax.ppermute(x, axis_name, perm) for x in dks)
    dvs = tuple(lax.ppermute(x, axis_name, perm) for x in dvs)

    def join(halves, dtype):
        return _unflat(
            jnp.concatenate(halves, axis=1), b, h
        ).astype(dtype)

    return (join(dqs, q.dtype), join(dks, k.dtype), join(dvs, v.dtype))


zigzag_flash_attention.defvjp(_zz_fwd, _zz_bwd)


def ulysses_attention(q, k, v, axis_name, causal=False, scale=None,
                      inner_attn=None, window=None):
    """All-to-all (DeepSpeed-Ulysses style) sequence-parallel attention.

    Call inside ``shard_map`` with local shards (B, S/n, H, D); requires
    ``H % n == 0`` (enforced by ``all_to_all``).  Reshards seq->heads,
    attends over the full sequence for the local head group, reshards back.

    ``inner_attn(q, k, v, causal=..., scale=...)`` overrides the
    full-sequence attention — the natural slot for the fused Pallas
    kernel (:func:`blendjax.ops.flash_attention`), since after the
    all-to-all each device holds the COMPLETE sequence for its head
    group and pays the O(S^2) score matrix right here.

    ``window`` passes straight to the inner attention (after the
    all-to-all each head group sees the full sequence, so sliding-window
    masking needs no cross-shard machinery here).
    """
    inner = inner_attn or full_attention
    n = lax.psum(1, axis_name)
    for name, arr in (("q", q), ("k", k), ("v", v)):
        if arr.shape[2] % n:
            raise ValueError(
                f"ulysses needs {name}'s head count ({arr.shape[2]}) "
                f"divisible by the sequence axis size ({n}); under GQA "
                "pick n_kv_heads as a multiple of the axis, or repeat "
                "kv heads upstream"
            )
    kwargs = dict(causal=causal, scale=scale)
    if window is not None:
        # only passed when set, so inner_attn closures predating the
        # window option keep working
        kwargs["window"] = window
    # (B, S/n, H, D) -> (B, S, H/n, D)
    qh = lax.all_to_all(q, axis_name, split_axis=2, concat_axis=1, tiled=True)
    kh = lax.all_to_all(k, axis_name, split_axis=2, concat_axis=1, tiled=True)
    vh = lax.all_to_all(v, axis_name, split_axis=2, concat_axis=1, tiled=True)
    out = inner(qh, kh, vh, **kwargs)
    # back to (B, S/n, H, D)
    return lax.all_to_all(out, axis_name, split_axis=1, concat_axis=2, tiled=True)


def make_ring_attention(
    mesh, seq_axis="seq", causal=False, impl="ring", batch_axis=None,
    head_axis=None, inner_attn=None, flash_interpret=None, window=None,
):
    """Wrap :func:`ring_attention` / :func:`ring_flash_attention` /
    :func:`ulysses_attention` for global arrays sharded
    ``P(batch_axis, seq_axis, head_axis, None)`` over ``mesh``.

    Returns ``attn(q, k, v) -> out`` usable directly under ``jax.jit``.
    ``inner_attn`` (ulysses only) swaps the per-head-group full-sequence
    attention, e.g. for the fused Pallas flash kernel;
    ``impl='ring_flash'`` instead fuses the kernel into the ring itself
    (``flash_interpret`` overrides the on/off-TPU interpreter choice).
    Composes with data parallelism (``batch_axis='data'``) and — ring
    variants only — with head-sharded tensor parallelism
    (``head_axis='model'``): each device then ring-rotates K/V for its
    head block, so sequence and tensor parallelism stack.  Ulysses
    repurposes the head axis for its all-to-all and cannot also shard it.

    ``window=W`` (causal only) is sliding-window attention.  The ring
    variants then rotate only ``ceil`` of window/shard steps — compute
    and ring traffic O(W) — and ulysses passes the window to its inner
    attention.  ``zigzag_flash`` rejects it: zigzag balances the FULL
    causal ring's triangular load, while a windowed ring's per-device
    work is already ~uniform (diagonal + the same few neighbor shards
    everywhere), so plain ``ring_flash`` is the windowed configuration.
    """
    if window is not None and not causal:
        raise ValueError("window requires causal=True")
    spec = P(batch_axis, seq_axis, head_axis, None)
    vary = tuple(a for a in (batch_axis, seq_axis, head_axis) if a is not None)
    if impl == "ring":
        inner = functools.partial(
            ring_attention, axis_name=seq_axis, causal=causal,
            vary_axes=vary, window=window,
        )
    elif impl == "ring_flash":
        if flash_interpret is None:
            flash_interpret = jax.default_backend() != "tpu"

        def inner(q, k, v, _axis=seq_axis, _vary=vary,
                  _interp=flash_interpret):
            # positional call: custom_vjp rejects nondiff args by keyword
            return ring_flash_attention(
                q, k, v, _axis, causal, None, _interp, _vary, window
            )
    elif impl == "zigzag_flash":
        if not causal:
            raise ValueError(
                "zigzag_flash balances the CAUSAL ring's load; a "
                "non-causal ring has no imbalance — use ring_flash"
            )
        if window is not None:
            raise ValueError(
                "zigzag_flash + window is pointless: the windowed ring "
                "is already load-balanced — use impl='ring_flash'"
            )
        if flash_interpret is None:
            flash_interpret = jax.default_backend() != "tpu"

        def inner(q, k, v, _axis=seq_axis, _vary=vary,
                  _interp=flash_interpret):
            return zigzag_flash_attention(
                q, k, v, _axis, None, _interp, _vary
            )
    elif impl == "ulysses":
        if head_axis is not None:
            raise ValueError("ulysses uses the head dim for its all-to-all; "
                             "head_axis sharding is ring-only")
        inner = functools.partial(ulysses_attention, axis_name=seq_axis,
                                  causal=causal, inner_attn=inner_attn,
                                  window=window)
    else:
        raise ValueError(f"unknown impl {impl!r} (want 'ring', "
                         "'ring_flash', 'zigzag_flash' or 'ulysses')")
    sm_kwargs = {}
    if impl in ("ring_flash", "zigzag_flash") and flash_interpret:
        # The Pallas HLO interpreter's grid-carry slicing trips
        # shard_map's vma typing for non-causal kernel instances (jax
        # 0.9; the error text itself recommends this flag as the
        # workaround).  Interpreter-only: the compiled TPU path keeps
        # full vma checking, and the parity tests check the numbers.
        sm_kwargs["check_vma"] = False
    try:
        mapped = shard_map(
            inner, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
            **sm_kwargs,
        )
    except TypeError:
        # older jax (the experimental shard_map fallback import) has no
        # check_vma kwarg — and no vma typing to work around either
        mapped = shard_map(
            inner, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec
        )

    n_seq = mesh.shape[seq_axis]

    def attn(q, k, v):
        sh = NamedSharding(mesh, spec)
        if impl == "zigzag_flash":
            # permute the global sequence into zigzag layout so each
            # contiguous shard holds a balanced (front, back) chunk
            # pair; undo on the way out.  Models that keep their whole
            # residual stream zigzag-permuted (with true positions in
            # the embeddings) can call zigzag_flash_attention directly
            # and skip these gathers.
            idx = jnp.asarray(_zigzag_perm(q.shape[1], n_seq))
            inv = jnp.argsort(idx)
            q, k, v = (jnp.take(x, idx, axis=1) for x in (q, k, v))
        q, k, v = (lax.with_sharding_constraint(x, sh) for x in (q, k, v))
        out = mapped(q, k, v)
        if impl == "zigzag_flash":
            out = jnp.take(out, inv, axis=1)
        return out

    return attn
