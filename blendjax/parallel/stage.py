"""``python -m blendjax.parallel.stage`` — one MPMD pipeline stage
process.

The launcher surface of the pipeline tier: :class:`~blendjax.parallel.
mpmd.StageFleet` spawns N of these (parent-allocated addresses and
``/dev/shm`` base prefixes on the command line, like every other
fleet), ``FleetWatchdog(restart=True)`` respawns one that dies with the
SAME command line, and the respawned stage restores its params from the
latest per-stage checkpoint cut so the driver's reconcile-replay
(docs/pipeline.md) resumes training crash-exactly.
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import signal
import threading


def main(argv=None):
    parser = argparse.ArgumentParser(
        description="blendjax MPMD pipeline stage process"
    )
    parser.add_argument("--address", required=True,
                        help="ZMQ REP bind address for this stage")
    parser.add_argument("--proc-index", type=int, required=True)
    parser.add_argument("--spec", required=True,
                        help="pipeline spec as a JSON object")
    parser.add_argument("--prev-address", default=None)
    parser.add_argument("--next-address", default=None)
    parser.add_argument("--shm-base", default=None,
                        help="parent-allocated /dev/shm name prefix")
    parser.add_argument("--ckpt-dir", default=None)
    parser.add_argument("--ckpt-every", type=int, default=1)
    parser.add_argument("--work-us", type=int, default=0,
                        help="benchmark compute stand-in: sleep this "
                             "many microseconds per owned layer unit "
                             "per direction")
    parser.add_argument("--log-level", default="INFO")
    args = parser.parse_args(argv)

    logging.basicConfig(
        level=args.log_level,
        format=f"%(asctime)s stage{args.proc_index} %(levelname)s "
               "%(message)s",
    )
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    from blendjax.parallel.mpmd import MpmdStage

    stage = MpmdStage(
        args.address, json.loads(args.spec), args.proc_index,
        prev_address=args.prev_address, next_address=args.next_address,
        shm_base=args.shm_base, ckpt_dir=args.ckpt_dir,
        ckpt_every=args.ckpt_every, work_us=args.work_us,
    )
    stop = threading.Event()

    def _stop(signum, frame):
        stop.set()

    signal.signal(signal.SIGTERM, _stop)
    signal.signal(signal.SIGINT, _stop)

    logging.getLogger("blendjax").info(
        "pipe stage %d/%d serving at %s (applied=%d)",
        stage.proc_index, stage.n_procs, stage.address, stage._applied,
    )
    try:
        stage.serve_forever(stop_event=stop)
    finally:
        stage.close()


if __name__ == "__main__":
    main()
