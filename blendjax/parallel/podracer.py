"""Sebulba sharded actor-learner plumbing (Podracer, arXiv:2104.06272).

The single-fleet :class:`~blendjax.models.actor_learner.ActorLearner`
tops out at one actor thread feeding one device: rollouts land via a
plain ``jax.device_put`` and the learner's gradient never leaves that
device.  This module owns everything between *N fleets* and a
*mesh-sharded learner*:

- :class:`FleetSet` — launches ``num_fleets`` independent Blender env
  fleets (each with its own :class:`~blendjax.btt.launcher.BlenderLauncher`,
  :class:`~blendjax.btt.envpool.EnvPool`,
  :class:`~blendjax.btt.supervise.FleetSupervisor`, per-fleet
  ``EventCounters``, and a disjoint port range) and aggregates their
  health into one snapshot (``fleet_id``-dimensioned counters);
- :class:`SegmentFanIn` — the queue fan-in: per-fleet rollout segments
  (time-major ``(T, n_f, ...)``, stacked straight into recycled
  per-fleet arena buffers) are assembled into ONE env-major global batch
  ``(N_padded, T, ...)`` in a pooled global arena, zero-filled + masked
  for divisibility padding and dead fleets, and placed **pre-sharded
  along the batch axis** through
  :func:`blendjax.btt.prefetch.put_batch` with
  :func:`blendjax.parallel.mesh.data_sharding` (``NamedSharding(mesh,
  P('data'))``) — so XLA sees a batch that is already split over the
  mesh and inserts the gradient psum on its own;
- :func:`make_segment_loss` — the masked env-major REINFORCE loss the
  sharded learner runs (same math as
  :func:`blendjax.models.policy.reinforce_loss` on the unmasked rows;
  the DP-equivalence test in ``tests/test_actor_learner_sharded.py``
  locks it).

Layout convention: the single-fleet path keeps the reference's
time-major ``(T, N)`` batches; the sharded path is **env-major**
``(N, T)`` so the *leading* axis is the batch axis and ``P('data')``
shards it directly (the put_batch divisibility error then names the axis
the caller actually controls).  Envs that don't divide the mesh's data
axis are padded with zero rows carried at weight 0 in ``batch['mask']``.

See docs/sharded_rl.md for the end-to-end recipe.
"""

from __future__ import annotations

import logging
import queue
import time

import numpy as np

from blendjax.btt.arena import ArenaBatch, ArenaPool

log = logging.getLogger("blendjax")

#: pytree keys of one rollout segment, in assembly order
SEGMENT_KEYS = ("obs", "actions", "rewards", "dones")


def padded_size(n, shard_count):
    """Smallest multiple of ``shard_count`` >= ``n`` (the global batch's
    padded env count; padding rows ride at mask weight 0)."""
    if shard_count <= 1:
        return n
    return -(-n // shard_count) * shard_count


def make_segment_loss(gamma=0.99, continuous=False):
    """Masked REINFORCE over ENV-MAJOR ``(N, T)`` segment batches.

    ``batch``: obs ``(N, T, D)``, actions ``(N, T[, A])``, rewards /
    dones ``(N, T)``, mask ``(N,)`` — weight 0 rows are divisibility
    padding or dead-fleet slices and contribute nothing to the loss,
    the baseline, or the advantage normalization.  On an all-ones mask
    this is exactly :func:`blendjax.models.policy.reinforce_loss` on the
    transposed batch (population-std advantage normalization included),
    so a sharded update matches a single-device update bit-for-allclose.
    """
    import jax
    import jax.numpy as jnp

    from blendjax.models import policy

    def loss_fn(p, batch):
        # returns scan over time: transpose to (T, N); the 'data' shard
        # stays on the env axis so the scan partitions cleanly
        returns = policy.discounted_returns(
            batch["rewards"].T, batch["dones"].T, gamma
        ).T  # (N, T)
        if continuous:
            logp = policy.gaussian_log_prob(p, batch["obs"], batch["actions"])
        else:
            logp = policy.categorical_log_prob(p, batch["obs"], batch["actions"])
        w = jnp.broadcast_to(
            batch["mask"].astype(jnp.float32)[:, None], returns.shape
        )
        wsum = jnp.maximum(w.sum(), 1.0)
        mu = (w * returns).sum() / wsum
        var = (w * (returns - mu) ** 2).sum() / wsum
        adv = (returns - mu) / (jnp.sqrt(var) + 1e-6)
        return -((w * logp * jax.lax.stop_gradient(adv)).sum() / wsum)

    return loss_fn


class SegmentFanIn:
    """Fan-in of per-fleet rollout segments into pre-sharded global batches.

    One bounded queue per fleet on the actor side; on the learner side
    :meth:`collect` pulls one segment from every *live* fleet (a fleet
    whose actor died is skipped once its queue drains — the learner never
    stalls on a dead fleet), :meth:`assemble` scatters them env-major into
    a recycled global arena with padding/dead rows zeroed and masked, and
    :meth:`to_device` places the batch through ``put_batch`` with the
    mesh's batch-axis sharding (or the default device when ``mesh`` is
    None — the unsharded multi-fleet ablation).

    Params
    ------
    fleet_sizes: sequence[int]
        Envs per fleet, in fleet order; fleet ``f`` owns global rows
        ``[offset_f, offset_f + n_f)``.
    mesh: jax.sharding.Mesh | None
        Learner mesh; the global env count pads up to a multiple of the
        ``axis`` size so every leaf shards evenly.
    axis: str
        Mesh axis the batch shards over.
    queue_size: int
        Segments buffered per fleet (bounds actor-policy staleness
        exactly like the single-fleet queue).
    arena_pool / fleet_arena_pools:
        Global-batch pool and per-fleet segment pools; sized from
        ``queue_size`` when omitted.  Per-fleet segment stacking and the
        global assembly both write into recycled arena buffers — the
        PR-1 feed discipline, driven by rollouts instead of the wire.
    """

    def __init__(self, fleet_sizes, mesh=None, axis="data", queue_size=4,
                 arena_pool=None, fleet_arena_pools=None):
        self.fleet_sizes = [int(n) for n in fleet_sizes]
        if not self.fleet_sizes or min(self.fleet_sizes) < 1:
            raise ValueError(f"bad fleet sizes {fleet_sizes}")
        self.num_fleets = len(self.fleet_sizes)
        self.offsets = np.concatenate([[0], np.cumsum(self.fleet_sizes)])
        self.n_real = int(self.offsets[-1])
        self.mesh = mesh
        self.axis = axis
        if mesh is not None:
            from blendjax.parallel.mesh import data_sharding

            self.shard_count = int(mesh.shape[axis])
            self.sharding = data_sharding(mesh, axis)
        else:
            self.shard_count = 1
            self.sharding = None
        self.n_padded = padded_size(self.n_real, self.shard_count)
        self.queues = [
            queue.Queue(maxsize=queue_size) for _ in range(self.num_fleets)
        ]
        self.arena_pool = arena_pool or ArenaPool(pool_size=3)
        self.fleet_arena_pools = fleet_arena_pools or [
            ArenaPool(pool_size=queue_size + 2)
            for _ in range(self.num_fleets)
        ]
        # per-scenario arenas (docs/scenarios.md): heterogeneous fleets
        # may carry per-scenario obs shapes, and one shared arena would
        # thrash reallocation flip-flopping between them.  Shape
        # signatures are interned; group 0 (the first signature seen —
        # the only one in a homogeneous run) keeps the unsuffixed
        # buffer names, so the homogeneous path is bit-identical.
        self._shape_groups = {}

    # -- actor side ----------------------------------------------------------

    def put_segment(self, fleet_id, seg_lists, stop_event):
        """Stack a finished segment straight into a recycled per-fleet
        arena buffer and enqueue it (bounded put, re-checked against
        ``stop_event``).  ``seg_lists`` is the actor's per-key list of
        per-step ``(n_f,...)`` arrays, ordered :data:`SEGMENT_KEYS`.
        Returns False once stop is set (the segment is dropped and its
        arena recycled)."""
        arena = self.fleet_arena_pools[fleet_id].acquire(
            stop_event=stop_event
        )
        if arena is None:
            return False
        data = {}
        for key, col in zip(SEGMENT_KEYS, seg_lists):
            first = np.asarray(col[0])
            buf = arena.get_buffer(
                key, (len(col),) + first.shape, first.dtype
            )
            np.stack(col, out=buf)
            data[key] = buf
        batch = ArenaBatch(data, arena)
        while not stop_event.is_set():
            try:
                self.queues[fleet_id].put(batch, timeout=0.2)
                return True
            except queue.Full:
                continue
        batch.recycle()
        return False

    # -- learner side --------------------------------------------------------

    def collect(self, alive_fn, stop_event, deadline=None, poll=0.2,
                min_ready=None):
        """One segment per live fleet: ``{fleet_id: ArenaBatch}``.

        A fleet with ``alive_fn(f)`` False AND an empty queue contributes
        nothing (its rows will be zero-masked); a live-but-slow fleet is
        waited on — quarantine keeps live fleets producing, so the only
        unbounded stall is every fleet dying, which the caller detects.
        Returns the partial dict immediately when ``stop_event`` sets or
        ``deadline`` (``time.monotonic`` seconds) passes — the caller
        must :meth:`recycle_segments` anything it does not assemble.

        ``min_ready`` (docs/scenarios.md, heterogeneous fleets): return
        as soon as at least that many live fleets have contributed —
        the fan-in analog of ``step_wait(min_ready=k)``.  A rich/slow
        scenario's fleet then rides into whichever update its segment
        lands in (its rows zero-masked meanwhile) instead of stalling
        every update to its frame rate.  None keeps the all-live
        barrier (the homogeneous default, bit-identical behavior)."""
        out = {}
        pending = set(range(self.num_fleets))
        while pending:
            if stop_event.is_set():
                break
            if deadline is not None and time.monotonic() >= deadline:
                break
            if min_ready is not None and len(out) >= min(
                min_ready,
                max(1, sum(1 for f in range(self.num_fleets)
                           if alive_fn(f))),
            ):
                break
            progressed = False
            for f in sorted(pending):
                try:
                    out[f] = self.queues[f].get_nowait()
                    pending.discard(f)
                    progressed = True
                except queue.Empty:
                    if not alive_fn(f):
                        # drain-then-drop: a dead actor may still owe a
                        # final enqueued segment
                        try:
                            out[f] = self.queues[f].get_nowait()
                            progressed = True
                        except queue.Empty:
                            pass
                        pending.discard(f)
            if pending and not progressed:
                # park on one pending queue instead of spinning
                f = min(pending)
                try:
                    out[f] = self.queues[f].get(timeout=poll)
                    pending.discard(f)
                except queue.Empty:
                    pass
        return out

    @staticmethod
    def recycle_segments(segs):
        for s in segs.values():
            s.recycle()

    @staticmethod
    def _shape_sig(seg):
        """Per-segment schema signature: key -> (sample shape, dtype)
        over the segment keys.  Segments sharing a signature assemble
        into one global batch; differing ones (heterogeneous scenario
        resolutions/obs dims) get their own group."""
        return tuple(
            (key, seg.data[key].shape[1:], str(seg.data[key].dtype))
            for key in SEGMENT_KEYS
        )

    def split_groups(self, segs):
        """Partition per-fleet segments by shape signature, interned
        first-seen-first: ``[(group_index, {fid: seg}), ...]`` in group
        order.  One group (every homogeneous run) is the common case."""
        groups = {}
        for f in sorted(segs):
            sig = self._shape_sig(segs[f])
            gid = self._shape_groups.setdefault(
                sig, len(self._shape_groups)
            )
            groups.setdefault(gid, {})[f] = segs[f]
        return sorted(groups.items())

    def assemble_groups(self, segs, stop_event=None, timeout=30.0):
        """:meth:`assemble` tolerant of per-scenario obs shapes: the
        segments are partitioned by shape signature and each group is
        assembled into its OWN full-width global batch (the other
        groups' fleet rows zero-masked), so a mixed-resolution fleet
        set never forces one global shape — the learner runs one
        masked update per group instead of crashing on a ragged
        stack.  Returns a list of ``ArenaBatch`` (singleton — and
        bit-identical to :meth:`assemble` — whenever shapes agree)."""
        out = []
        try:
            for gid, group in self.split_groups(segs):
                out.append(self.assemble(
                    group, stop_event=stop_event, timeout=timeout,
                    _group=gid,
                ))
                if out[-1] is None:
                    out.pop()
        except BaseException:
            for b in out:
                b.recycle()
            raise
        return out

    def assemble(self, segs, stop_event=None, timeout=30.0, _group=0):
        """Scatter per-fleet segments into one env-major global batch.

        Returns an :class:`ArenaBatch` whose data is ``{obs, actions,
        rewards, dones, mask}`` with leading axis ``n_padded`` — rows of
        absent fleets and divisibility padding zero-filled and carried at
        ``mask`` 0.  Fleet arenas recycle as soon as their rows are
        copied; the global arena recycles after the device transfer
        (:meth:`to_device`).  Segments must share one shape signature —
        route mixed-scenario sets through :meth:`assemble_groups`."""
        if not segs:
            raise ValueError("assemble needs at least one fleet segment")
        arena = self.arena_pool.acquire(timeout=timeout, stop_event=stop_event)
        if arena is None:
            if stop_event is not None and stop_event.is_set():
                self.recycle_segments(segs)
                return None
            raise TimeoutError(
                f"no global batch arena freed within {timeout:.1f}s "
                f"(pool size {self.arena_pool.pool_size}); the learner "
                "has stalled or the pool is undersized"
            )
        first = next(iter(segs.values())).data
        t_len = first["rewards"].shape[0]
        # group > 0 buffers get their own arena paths so heterogeneous
        # shape groups never thrash each other's preallocations (group
        # 0 keeps the plain names: homogeneous runs are untouched)
        suffix = "" if _group == 0 else f"@g{_group}"
        data = {}
        for key in SEGMENT_KEYS:
            tail = first[key].shape[2:]
            buf = arena.get_buffer(
                key + suffix, (self.n_padded, t_len) + tail,
                first[key].dtype
            )
            data[key] = buf
        mask = arena.get_buffer(
            "mask" + suffix, (self.n_padded,), np.float32
        )
        mask[:] = 0.0
        for f, seg in segs.items():
            o, n = int(self.offsets[f]), self.fleet_sizes[f]
            for key in SEGMENT_KEYS:
                # (T, n, ...) -> (n, T, ...) at the fleet's global offset
                np.copyto(data[key][o:o + n], seg.data[key].swapaxes(0, 1))
            mask[o:o + n] = 1.0
            seg.recycle()
        # zero the rows nobody wrote (dead fleets + padding): arenas
        # recycle, so stale bytes from a previous batch would otherwise
        # leak into the (masked, but still computed-on) rows
        dead = mask == 0.0
        if dead.any():
            for key in SEGMENT_KEYS:
                data[key][dead] = 0
        data["mask"] = mask
        return ArenaBatch(data, arena)

    def to_device(self, batch):
        """Place an assembled global batch pre-sharded on the mesh (or
        the default device) and recycle its arena once the transfer has
        completed — the same recycle-after-transfer contract as
        :func:`blendjax.btt.prefetch.device_prefetch`."""
        import jax

        from blendjax.btt.prefetch import own_arena_leaves, put_batch

        host = batch.data
        if jax.default_backend() == "cpu":
            # CPU device_put zero-copies aligned numpy arrays; recycling
            # below would let the next assembly rewrite this batch in
            # place (the PR-5 aliasing bug, same fix)
            host = own_arena_leaves(host, batch.arena)
        dev = put_batch(host, self.sharding)
        jax.block_until_ready(dev)
        batch.recycle()
        return dev


class FleetSet:
    """N independent env fleets with one aggregate health surface.

    Launches ``num_fleets`` fleets of ``envs_per_fleet`` producers each:
    fleet ``f`` binds ports from ``start_port + f * port_stride`` (so
    fleets never collide), steps through its own
    :class:`~blendjax.btt.envpool.EnvPool` (quarantining, per-fleet
    ``EventCounters``) and is watched by its own
    :class:`~blendjax.btt.supervise.FleetSupervisor` carrying
    ``fleet_id=f``.  :meth:`health` aggregates every fleet's snapshot —
    counters summed, quarantine masks concatenated — via
    :func:`blendjax.btt.supervise.aggregate_health`.

    Use as a context manager; pass ``fleet_set.pools`` (or the set
    itself) to :class:`~blendjax.models.actor_learner.ActorLearner`.

    Scenario plane (docs/scenarios.md): ``ctrl=True`` allocates a
    second named socket (``CTRL``) per instance — the duplex control
    endpoints, exposed per fleet on :attr:`ctrl_addresses` in exactly
    the shape :class:`~blendjax.scenario.DomainRandomizer` takes — and
    ``fleet_env_kwargs`` (one dict per fleet, layered over the shared
    ``**env_kwargs``) launches HETEROGENEOUS fleets: per-scenario
    physics rates, resolutions or scene params from the first frame
    (e.g. ``fleet_env_kwargs=[spec.env_kwargs() for spec in ...]``).
    """

    def __init__(self, scene, script, num_fleets, envs_per_fleet, *,
                 background=True, start_port=21000, port_stride=100,
                 timeoutms=None, fault_policy=None, supervise=True,
                 interval=0.5, restart=True, ctrl=False,
                 fleet_env_kwargs=None, **env_kwargs):
        if num_fleets < 1 or envs_per_fleet < 1:
            raise ValueError("num_fleets and envs_per_fleet must be >= 1")
        sockets_per_env = 2 if ctrl else 1
        if envs_per_fleet * 2 * sockets_per_env > port_stride:
            # each instance binds one port per named socket (launchers
            # may probe past collisions, hence the 2x margin): a fleet
            # spilling into the next fleet's range would crosstalk with
            # no useful error
            raise ValueError(
                f"envs_per_fleet={envs_per_fleet} does not fit in "
                f"port_stride={port_stride}; raise port_stride to at "
                f"least {2 * sockets_per_env}x the fleet size"
            )
        if fleet_env_kwargs is not None \
                and len(fleet_env_kwargs) != num_fleets:
            raise ValueError(
                f"fleet_env_kwargs names {len(fleet_env_kwargs)} fleets, "
                f"num_fleets={num_fleets}"
            )
        self.num_fleets = num_fleets
        self.envs_per_fleet = envs_per_fleet
        self._cfg = dict(
            scene=scene, script=script, background=background,
            start_port=start_port, port_stride=port_stride,
            timeoutms=timeoutms, fault_policy=fault_policy,
            supervise=supervise, interval=interval, restart=restart,
            ctrl=bool(ctrl), fleet_env_kwargs=fleet_env_kwargs,
            env_kwargs=env_kwargs,
        )
        self.launchers = []
        self.pools = []
        self.supervisors = []
        #: per-fleet CTRL endpoint lists (``ctrl=True`` only) — the
        #: scenario plane's control addresses, in the shape
        #: :class:`~blendjax.scenario.DomainRandomizer` takes
        self.ctrl_addresses = []
        self._stack = []

    def __enter__(self):
        from blendjax.btt.constants import DEFAULT_TIMEOUTMS
        from blendjax.btt.env import kwargs_to_cli
        from blendjax.btt.envpool import EnvPool
        from blendjax.btt.launcher import BlenderLauncher
        from blendjax.btt.supervise import FleetSupervisor
        from blendjax.utils.timing import EventCounters

        cfg = self._cfg
        sockets = ["GYM"] + (["CTRL"] if cfg["ctrl"] else [])
        try:
            for f in range(self.num_fleets):
                # per-fleet overrides layered over the shared kwargs:
                # heterogeneous fleets (per-scenario physics rates /
                # scene params, docs/scenarios.md) differ only here
                fkw = dict(cfg["env_kwargs"])
                if cfg["fleet_env_kwargs"] is not None:
                    fkw.update(cfg["fleet_env_kwargs"][f] or {})
                bl = BlenderLauncher(
                    scene=cfg["scene"],
                    script=cfg["script"],
                    num_instances=self.envs_per_fleet,
                    named_sockets=sockets,
                    start_port=cfg["start_port"] + f * cfg["port_stride"],
                    background=cfg["background"],
                    instance_args=[
                        list(kwargs_to_cli(fkw))
                        for _ in range(self.envs_per_fleet)
                    ],
                )
                bl.__enter__()
                self._stack.append(bl)
                self.launchers.append(bl)
                if cfg["ctrl"]:
                    self.ctrl_addresses.append(
                        list(bl.launch_info.addresses["CTRL"])
                    )
            for f, bl in enumerate(self.launchers):
                counters = EventCounters()
                pool = EnvPool(
                    bl.launch_info.addresses["GYM"],
                    timeoutms=cfg["timeoutms"] or DEFAULT_TIMEOUTMS,
                    fault_policy=cfg["fault_policy"],
                    counters=counters,
                )
                self.pools.append(pool)
                if cfg["supervise"]:
                    sup = FleetSupervisor(
                        bl, pool=pool, interval=cfg["interval"],
                        restart=cfg["restart"], counters=counters,
                        fleet_id=f,
                    )
                    sup.start()
                    self.supervisors.append(sup)
        except BaseException:
            self.close()
            raise
        return self

    def health(self):
        """Aggregate multi-fleet health snapshot (see
        :func:`blendjax.btt.supervise.aggregate_health`)."""
        from blendjax.btt.supervise import aggregate_health

        return aggregate_health(self.supervisors)

    def close(self):
        for sup in self.supervisors:
            try:
                sup.stop()
            except Exception:
                log.exception("fleet supervisor stop failed")
        self.supervisors = []
        for pool in self.pools:
            try:
                pool.close()
            except Exception:
                log.exception("fleet pool close failed")
        self.pools = []
        while self._stack:
            bl = self._stack.pop()
            try:
                bl.__exit__(None, None, None)
            except Exception:
                log.exception("fleet launcher shutdown failed")
        self.launchers = []

    def __exit__(self, *exc):
        self.close()
        return False
