"""Sharding rules and the mesh-sharded train step.

How blendjax scales model-side (SURVEY.md §2.4: the reference has *no*
model parallelism — consumer scale-out there is DataLoader workers only):

- **data axis**: the stream feeds per-host batch shards
  (``BatchLoader(shard=(process_index, process_count))``), the batch is
  sharded ``P('data')``, and XLA turns the gradient sum into a psum over
  ICI.
- **model axis**: wide dense layers shard their output features
  ``P(None, 'model')``; XLA inserts the all-gather/reduce-scatter pairs.

Rules map pytree paths to PartitionSpecs; anything unmatched replicates.
"""

from __future__ import annotations

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from blendjax.models.train import TrainState


def detector_rules(axis="model"):
    """Tensor-parallel rules for :mod:`blendjax.models.detector`: the two
    dense layers carry the parameter mass and split their features; convs
    replicate (tiny, bandwidth-bound)."""
    return {
        ("fc", "w"): P(None, axis),
        ("fc", "b"): P(axis),
        ("head", "w"): P(axis, None),  # row-parallel: consumes fc's sharded out
        ("head", "b"): P(),
    }


def seqformer_rules(model_axis="model", expert_axis=None):
    """Sharding rules for :mod:`blendjax.models.seqformer`.

    Attention projections shard over the head axis (head-major layout),
    the MLP is column/row tensor-parallel, and MoE expert stacks shard
    over ``expert_axis`` (defaults to ``model_axis`` when the mesh has no
    dedicated expert axis) so the gate-weighted mixture psums over expert
    shards.
    """
    e = expert_axis or model_axis
    return {
        ("wq", "w"): P(None, model_axis, None),
        ("wq", "b"): P(model_axis, None),
        ("wk", "w"): P(None, model_axis, None),
        ("wk", "b"): P(model_axis, None),
        ("wv", "w"): P(None, model_axis, None),
        ("wv", "b"): P(model_axis, None),
        ("wo", "w"): P(model_axis, None, None),
        ("wo", "b"): P(),
        ("mlp", "fc", "w"): P(None, model_axis),
        ("mlp", "fc", "b"): P(model_axis),
        ("mlp", "proj", "w"): P(model_axis, None),
        ("mlp", "proj", "b"): P(),
        ("moe", "w1"): P(e, None, None),
        ("moe", "b1"): P(e, None),
        ("moe", "w2"): P(e, None, None),
        ("moe", "b2"): P(e, None),
    }


def _path_key(path):
    out = []
    for p in path:
        if hasattr(p, "key"):
            out.append(p.key)
        elif hasattr(p, "idx"):
            out.append(p.idx)
        elif hasattr(p, "name"):
            out.append(p.name)
    return tuple(out)


def param_specs(params, rules):
    """PartitionSpec pytree for ``params``: longest-suffix match of each
    leaf path against ``rules`` keys; default replicate."""

    def spec_for(path):
        key = _path_key(path)
        for rule_key, spec in rules.items():
            if key[-len(rule_key):] == tuple(rule_key):
                return spec
        return P()

    return jax.tree_util.tree_map_with_path(lambda path, _: spec_for(path), params)


def shard_pytree(tree, mesh, specs):
    """Place a pytree on the mesh according to a spec pytree."""
    return jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
        tree,
        specs,
        is_leaf=lambda x: x is None,
    )


def make_sharded_train_step(loss_fn, optimizer, mesh, rules=None, data_axis="data"):
    """Build ``(init_sharded, step)`` for SPMD training over ``mesh``.

    ``init_sharded(params)`` places params (and fresh optimizer state)
    according to ``rules``; ``step(state, batch)`` is jitted with sharded
    in/out so XLA lays gradients' psum over the data axis and the tensor-
    parallel collectives over the model axis automatically.  The batch must
    arrive sharded ``P(data_axis)`` (use
    ``JaxStream(sharding=data_sharding(mesh))``).
    """
    rules = rules or {}

    def init_sharded(params):
        specs = param_specs(params, rules)
        params = shard_pytree(params, mesh, specs)
        opt_state = optimizer.init(params)  # inherits param shardings
        return TrainState(params=params, opt_state=opt_state, step=0)

    def _step(state, batch):
        import optax

        loss, grads = jax.value_and_grad(loss_fn)(state.params, batch)
        updates, opt_state = optimizer.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        return TrainState(params, opt_state, state.step + 1), loss

    return init_sharded, jax.jit(_step, donate_argnums=(0,))


def make_seqformer_train_step(
    optimizer,
    mesh,
    data_axis="data",
    seq_axis="seq",
    model_axis="model",
    expert_axis=None,
    attn_impl="ring",
    moe_impl="dense",
    moe_k=2,
    moe_capacity_factor=1.25,
    moe_aux_weight=0.0,
    compute_dtype=None,
    flash_interpret=None,
    attn_window=None,
):
    """4-way-parallel training step for the SeqFormer world-model.

    Composes every parallelism the framework supports in one jitted step:
    batch dp-sharded over ``data_axis``, sequence sharded over
    ``seq_axis`` — ``attn_impl`` picks the scheme: ``'ring'`` (blockwise
    ring), ``'ring_flash'`` (the fused Pallas kernel per ring block
    pair, the long-context configuration), ``'zigzag_flash'`` (ring +
    flash with the load-balanced zigzag chunk layout — every device
    does equal causal work), ``'ulysses'`` (all-to-all),
    or ``'ulysses_flash'`` (all-to-all with the fused kernel as the
    per-head-group inner attention) — attention heads + MLP
    tensor-parallel over ``model_axis`` (ring variants only), MoE
    experts over ``expert_axis`` (see :func:`seqformer_rules`).
    ``moe_impl='topk'`` switches the expert layer from the dense mixture
    to routed expert parallelism (top-k gating + capacity,
    :mod:`blendjax.models.moe`) with an optional load-balance aux loss.
    ``attn_window=W`` enables sliding-window attention through whichever
    scheme is selected (ring variants then rotate only the shards the
    window reaches — compute and ring traffic O(W); zigzag rejects it,
    the windowed ring is already balanced).

    Returns ``(init_sharded, step, batch_sharding)``; device_put batches
    with ``batch_sharding`` (leading dims sharded data x seq).
    """
    import functools

    from blendjax.models import seqformer
    from blendjax.parallel.ring_attention import make_ring_attention

    inner_attn = None
    if attn_impl == "ulysses_flash":
        from blendjax.ops.flash_attention import (
            flash_attention,
            flash_block_size,
        )

        attn_impl = "ulysses"
        # compiled kernel on TPU; the interpreter elsewhere keeps the
        # option runnable on the CPU mesh used in CI.
        # ``flash_interpret`` overrides (tests/test_tpu_lowering.py
        # forces the compiled path when EXPORTING for tpu from a CPU
        # host — the auto rule would silently export the interpreter
        # lowering and prove nothing about Mosaic)
        if flash_interpret is None:
            interpret = jax.default_backend() != "tpu"
        else:
            interpret = flash_interpret

        def inner_attn(q, k, v, causal=False, scale=None, window=None):
            # one tile-selection policy for the ulysses and ring paths
            blk = flash_block_size(q.shape[1])
            return flash_attention(
                q, k, v, causal, scale, blk, blk, interpret, window
            )
    attn = make_ring_attention(
        mesh,
        seq_axis=seq_axis,
        causal=True,
        impl=attn_impl,
        batch_axis=data_axis,
        head_axis=(model_axis
                   if attn_impl in ("ring", "ring_flash", "zigzag_flash")
                   else None),
        inner_attn=inner_attn,
        flash_interpret=(flash_interpret
                         if attn_impl in ("ring_flash", "zigzag_flash")
                         else None),
        window=attn_window,
    )
    rules = seqformer_rules(model_axis, expert_axis)
    loss_kwargs = dict(
        attn_fn=attn,
        moe_impl=moe_impl,
        moe_k=moe_k,
        moe_capacity_factor=moe_capacity_factor,
        moe_aux_weight=moe_aux_weight,
    )
    if compute_dtype is not None:
        # passthrough (default stays the model's bf16): single-device
        # parity checks pin f32 so sharded-vs-reference agreement is
        # numerically tight
        loss_kwargs["compute_dtype"] = compute_dtype
    loss = functools.partial(seqformer.loss_fn, **loss_kwargs)
    init_sharded, step = make_sharded_train_step(
        loss, optimizer, mesh, rules=rules, data_axis=data_axis
    )
    batch_sharding = NamedSharding(mesh, P(data_axis, seq_axis, None))
    return init_sharded, step, batch_sharding
