"""Pipeline parallelism: GPipe-style microbatch pipelining over a mesh axis.

The reference has no model parallelism of any kind (SURVEY.md §2.4);
blendjax provides the full TPU-native set.  This module is the pipeline
leg: the model is split into S stages whose parameters stack on a leading
stage axis sharded ``P(pipe_axis)`` — one stage per device group — and
microbatches flow stage-to-stage over ICI with ``lax.ppermute``, the
idiomatic XLA/SPMD pipelining pattern (no send/recv primitives, no
schedulers: one ``lax.scan`` over clock ticks, collectives inserted by
XLA).

Schedule: with M microbatches and S stages the scan runs M + S - 1 ticks;
at tick t stage s works on microbatch t - s (bubble ticks compute values
that are masked out of the collected output).  Reverse-mode AD through
the scan + ppermute gives the backward schedule automatically.

Usage::

    stage_fn(stage_params, x) -> y            # one stage, same x/y shape
    stacked = stack_stage_params([p0, p1, ...])   # leading stage axis
    apply = make_pipeline(stage_fn, mesh, pipe_axis='pipe')
    y = apply(stacked, x)                     # x: (M, mb, ...) microbatched

Constraints: one stage per pipe-axis shard (stack size == axis size) and
stage input/output shapes equal (they ride the same ppermute buffer).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

try:
    from jax import shard_map  # jax >= 0.8
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map

from blendjax.parallel.ring_attention import _pvary


def stack_stage_params(stage_params_list):
    """Stack per-stage param pytrees on a new leading stage axis."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *stage_params_list)


def unstack_stage_params(stacked, n_stages):
    """Inverse of :func:`stack_stage_params`."""
    return [jax.tree.map(lambda x, i=i: x[i], stacked) for i in range(n_stages)]


def pipeline(stage_params, x, stage_fn, axis_name, vary_axes=None):
    """Run the pipeline *inside* ``shard_map``.

    ``stage_params``: this shard's stage params (leading stage axis of
    local size 1, squeezed here).  ``x``: microbatched input (M, mb, ...)
    replicated over the pipe axis.  Returns (M, mb, ...) final-stage
    outputs, replicated over the pipe axis via a masked psum.
    """
    n = lax.psum(1, axis_name)
    me = lax.axis_index(axis_name)
    params = jax.tree.map(lambda p: p[0], stage_params)  # drop stage axis
    m = x.shape[0]
    axes = tuple(vary_axes) if vary_axes else (axis_name,)
    # Stage s receives stage s-1's output.
    perm = [(j, (j + 1) % n) for j in range(n)]

    def tick(carry, t):
        acc, state = carry
        # Stage 0 ingests microbatch t (clamped on bubble ticks); other
        # stages ingest the neighbor's previous output.
        mb = lax.dynamic_index_in_dim(x, jnp.clip(t, 0, m - 1), keepdims=False)
        inp = jnp.where(me == 0, _pvary(mb, (axis_name,)), state)
        out = stage_fn(params, inp)
        # The last stage finished microbatch t - (n - 1) this tick.
        widx = t - (n - 1)
        upd = lax.dynamic_update_index_in_dim(acc, out, jnp.maximum(widx, 0), 0)
        acc = jnp.where((me == n - 1) & (widx >= 0), upd, acc)
        state = lax.ppermute(out, axis_name, perm)
        return (acc, state), None

    acc0 = _pvary(jnp.zeros((m,) + x.shape[1:], x.dtype), axes)
    state0 = _pvary(jnp.zeros(x.shape[1:], x.dtype), axes)
    (acc, _), _ = lax.scan(tick, (acc0, state0), jnp.arange(m + n - 1))
    # Only the last stage holds real outputs; mask and psum replicates the
    # result across the pipe axis.
    return lax.psum(jnp.where(me == n - 1, acc, 0), axis_name)


def make_pipeline(stage_fn, mesh, pipe_axis="pipe", x_spec=None):
    """Wrap :func:`pipeline` for globally-sharded stacked stage params.

    ``x_spec``: PartitionSpec of the microbatched input *excluding* the
    pipe axis (e.g. ``P(None, 'data')`` to keep the per-microbatch batch
    dim data-sharded); defaults to fully replicated.  Returns
    ``apply(stacked_params, x)`` usable under ``jax.jit``.
    """
    x_spec = x_spec if x_spec is not None else P()
    n = mesh.shape[pipe_axis]
    vary = (pipe_axis,) + tuple(
        a for axes in x_spec if axes is not None
        for a in ((axes,) if isinstance(axes, str) else axes)
    )
    inner = functools.partial(
        pipeline, stage_fn=stage_fn, axis_name=pipe_axis, vary_axes=vary
    )
    mapped = shard_map(
        inner, mesh=mesh, in_specs=(P(pipe_axis), x_spec), out_specs=x_spec
    )

    def apply(stacked_params, x):
        n_stages = jax.tree.leaves(stacked_params)[0].shape[0]
        if n_stages != n:
            raise ValueError(
                f"stacked params have {n_stages} stages but mesh axis "
                f"{pipe_axis!r} has size {n} (need exactly one per shard)"
            )
        stacked_params = jax.tree.map(
            lambda p: lax.with_sharding_constraint(
                p, NamedSharding(mesh, P(pipe_axis))
            ),
            stacked_params,
        )
        return mapped(stacked_params, x)

    return apply


def microbatch(batch, num_microbatches):
    """Host/device-side reshape (B, ...) -> (M, B/M, ...) for the pipeline."""
    def split(x):
        b = x.shape[0]
        if b % num_microbatches:
            raise ValueError(
                f"batch {b} not divisible by {num_microbatches} microbatches"
            )
        return x.reshape((num_microbatches, b // num_microbatches) + x.shape[1:])

    return jax.tree.map(split, batch)
