"""Pipeline parallelism: GPipe-style microbatch pipelining over a mesh axis.

The reference has no model parallelism of any kind (SURVEY.md §2.4);
blendjax provides the full TPU-native set.  This module is the pipeline
leg: the model is split into S stages whose parameters stack on a leading
stage axis sharded ``P(pipe_axis)`` — one stage per device group — and
microbatches flow stage-to-stage over ICI with ``lax.ppermute``, the
idiomatic XLA/SPMD pipelining pattern (no send/recv primitives, no
schedulers: one ``lax.scan`` over clock ticks, collectives inserted by
XLA).

Schedule: with M microbatches and S stages the scan runs M + S - 1 ticks;
at tick t stage s works on microbatch t - s (bubble ticks compute values
that are masked out of the collected output).  Reverse-mode AD through
the scan + ppermute gives the backward schedule automatically.

Usage::

    stage_fn(stage_params, x) -> y            # one stage, same x/y shape
    stacked = stack_stage_params([p0, p1, ...])   # leading stage axis
    apply = make_pipeline(stage_fn, mesh, pipe_axis='pipe')
    y = apply(stacked, x)                     # x: (M, mb, ...) microbatched

Constraints: one stage per pipe-axis shard (stack size == axis size) and
stage input/output shapes equal (they ride the same ppermute buffer).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

try:
    from jax import shard_map  # jax >= 0.8
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map

from blendjax.parallel.ring_attention import _pvary


def stack_stage_params(stage_params_list):
    """Stack per-stage param pytrees on a new leading stage axis."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *stage_params_list)


def unstack_stage_params(stacked, n_stages):
    """Inverse of :func:`stack_stage_params`."""
    return [jax.tree.map(lambda x, i=i: x[i], stacked) for i in range(n_stages)]


def pipeline(stage_params, x, stage_fn, axis_name, vary_axes=None):
    """Run the pipeline *inside* ``shard_map``.

    ``stage_params``: this shard's stage params (leading stage axis of
    local size 1, squeezed here).  ``x``: microbatched input (M, mb, ...)
    replicated over the pipe axis.  Returns (M, mb, ...) final-stage
    outputs, replicated over the pipe axis via a masked psum.
    """
    n = lax.psum(1, axis_name)
    me = lax.axis_index(axis_name)
    params = jax.tree.map(lambda p: p[0], stage_params)  # drop stage axis
    m = x.shape[0]
    axes = tuple(vary_axes) if vary_axes else (axis_name,)
    # Stage s receives stage s-1's output.
    perm = [(j, (j + 1) % n) for j in range(n)]

    def tick(carry, t):
        acc, state = carry
        # Stage 0 ingests microbatch t (clamped on bubble ticks); other
        # stages ingest the neighbor's previous output.
        mb = lax.dynamic_index_in_dim(x, jnp.clip(t, 0, m - 1), keepdims=False)
        inp = jnp.where(me == 0, _pvary(mb, (axis_name,)), state)
        out = stage_fn(params, inp)
        # The last stage finished microbatch t - (n - 1) this tick.
        widx = t - (n - 1)
        upd = lax.dynamic_update_index_in_dim(acc, out, jnp.maximum(widx, 0), 0)
        acc = jnp.where((me == n - 1) & (widx >= 0), upd, acc)
        state = lax.ppermute(out, axis_name, perm)
        return (acc, state), None

    acc0 = _pvary(jnp.zeros((m,) + x.shape[1:], x.dtype), axes)
    state0 = _pvary(jnp.zeros(x.shape[1:], x.dtype), axes)
    (acc, _), _ = lax.scan(tick, (acc0, state0), jnp.arange(m + n - 1))
    # Only the last stage holds real outputs; mask and psum replicates the
    # result across the pipe axis.
    return lax.psum(jnp.where(me == n - 1, acc, 0), axis_name)


def make_pipeline(stage_fn, mesh, pipe_axis="pipe", x_spec=None):
    """Wrap :func:`pipeline` for globally-sharded stacked stage params.

    ``x_spec``: PartitionSpec of the microbatched input *excluding* the
    pipe axis (e.g. ``P(None, 'data')`` to keep the per-microbatch batch
    dim data-sharded); defaults to fully replicated.  Returns
    ``apply(stacked_params, x)`` usable under ``jax.jit``.
    """
    x_spec = x_spec if x_spec is not None else P()
    n = mesh.shape[pipe_axis]
    vary = (pipe_axis,) + tuple(
        a for axes in x_spec if axes is not None
        for a in ((axes,) if isinstance(axes, str) else axes)
    )
    inner = functools.partial(
        pipeline, stage_fn=stage_fn, axis_name=pipe_axis, vary_axes=vary
    )
    mapped = shard_map(
        inner, mesh=mesh, in_specs=(P(pipe_axis), x_spec), out_specs=x_spec
    )

    def apply(stacked_params, x):
        n_stages = jax.tree.leaves(stacked_params)[0].shape[0]
        if n_stages != n:
            raise ValueError(
                f"stacked params have {n_stages} stages but mesh axis "
                f"{pipe_axis!r} has size {n} (need exactly one per shard)"
            )
        stacked_params = jax.tree.map(
            lambda p: lax.with_sharding_constraint(
                p, NamedSharding(mesh, P(pipe_axis))
            ),
            stacked_params,
        )
        return mapped(stacked_params, x)

    return apply


def _identity_proj(_params, x):
    return x


def _zeros_like_tree(t):
    return jax.tree.map(jnp.zeros_like, t)


def _fwd_loss(stage_params, proj_params, x, targets, *, stage_fn, loss_fn,
              in_proj, out_proj, axis_name, vary_axes):
    """GPipe forward (inside shard_map) that reduces straight to the mean
    microbatch loss; reverse-mode AD through the scan gives the classic
    GPipe backward (all M microbatch activations live across the forward
    sweep — the memory profile 1F1B exists to avoid)."""
    n = lax.psum(1, axis_name)
    me = lax.axis_index(axis_name)
    params = jax.tree.map(lambda p: p[0], stage_params)
    ep, rp = proj_params
    m = x.shape[0]
    perm = [(j, (j + 1) % n) for j in range(n)]
    wire = jax.eval_shape(in_proj, ep, jax.eval_shape(lambda a: a[0], x))

    def tick(carry, t):
        state, loss_acc = carry
        mb = lax.dynamic_index_in_dim(x, jnp.clip(t, 0, m - 1), keepdims=False)
        inp = jnp.where(me == 0, _pvary(in_proj(ep, mb), vary_axes), state)
        out = stage_fn(params, inp)
        widx = t - (n - 1)
        tgt = lax.dynamic_index_in_dim(
            targets, jnp.clip(widx, 0, m - 1), keepdims=False
        )
        lj = loss_fn(out_proj(rp, out), tgt)
        loss_acc = loss_acc + jnp.where(
            (me == n - 1) & (widx >= 0) & (widx < m), lj, 0.0
        )
        return (lax.ppermute(out, axis_name, perm), loss_acc), None

    state0 = _pvary(jnp.zeros(wire.shape, wire.dtype), vary_axes)
    loss0 = _pvary(jnp.zeros((), jnp.float32), vary_axes)
    (_, loss_acc), _ = lax.scan(tick, (state0, loss0), jnp.arange(m + n - 1))
    return lax.psum(loss_acc, axis_name) / m


def _1f1b_grads(stage_params, proj_params, x, targets, *, stage_fn, loss_fn,
                in_proj, out_proj, axis_name, vary_axes):
    """1F1B (eager-backward) pipeline training step inside shard_map.

    Schedule: iteration ``k`` runs forward for microbatch ``k - s`` on
    stage ``s`` and backward for microbatch ``k - (2(n-1) - s)`` — the
    last stage backpropagates a microbatch the same iteration its forward
    completes, so at most ``2(n-1-s)+1`` activations are ever live per
    stage (a ring buffer of ``2n-1``), independent of the microbatch
    count M.  GPipe-by-AD instead holds all M.  Backward recomputes the
    stage forward from the saved stage *input* (rematerialization), the
    standard trade on HBM-bound TPUs.

    Returns ``(loss, stage_grads[local 1, ...], (d_in_proj, d_out_proj))``
    with gradients averaged over microbatches; projection grads are
    psum-replicated, stage grads stay stage-sharded.
    """
    n = lax.psum(1, axis_name)
    me = lax.axis_index(axis_name)
    params = jax.tree.map(lambda p: p[0], stage_params)
    ep, rp = proj_params
    m = x.shape[0]
    L = 2 * n - 1  # ring-buffer depth: max in-flight activations + 1
    # Differentiating wrt a REPLICATED (non-varying) input under shard_map
    # makes AD insert a psum for the cotangent; inside the role switch that
    # collective would run on a subset of devices and deadlock.  Cast the
    # proj params varying up front; the accumulated grads are psum'd once,
    # uniformly, at the end.
    ep = jax.tree.map(lambda p: _pvary(p, vary_axes), ep)
    rp = jax.tree.map(lambda p: _pvary(p, vary_axes), rp)
    x = _pvary(x, vary_axes)
    targets = _pvary(targets, vary_axes)
    perm_fwd = [(j, (j + 1) % n) for j in range(n)]
    perm_bwd = [((j + 1) % n, j) for j in range(n)]
    wire = jax.eval_shape(in_proj, ep, jax.eval_shape(lambda a: a[0], x))

    def pv(val):
        return _pvary(val, vary_axes)

    def tick(carry, k):
        acc_p, acc_e, acc_r, act_buf, fwd_wire, bwd_wire, loss_acc = carry

        # ---- forward unit: microbatch j_f = k - me -----------------------
        j_f = k - me
        fwd_active = (j_f >= 0) & (j_f < m)
        mb_f = lax.dynamic_index_in_dim(
            x, jnp.clip(j_f, 0, m - 1), keepdims=False
        )
        inp = jnp.where(me == 0, pv(in_proj(ep, mb_f)), fwd_wire)
        out = stage_fn(params, inp)
        act_buf = jnp.where(
            fwd_active,
            lax.dynamic_update_index_in_dim(
                act_buf, inp, jnp.mod(jnp.maximum(j_f, 0), L), 0
            ),
            act_buf,
        )

        # ---- backward unit: microbatch j_b = k - (2(n-1) - me) -----------
        j_b = k - (2 * (n - 1) - me)
        bwd_active = (j_b >= 0) & (j_b < m)
        jb_c = jnp.clip(j_b, 0, m - 1)
        xs = lax.dynamic_index_in_dim(
            act_buf, jnp.mod(jb_c, L), keepdims=False
        )
        mb_b = lax.dynamic_index_in_dim(x, jb_c, keepdims=False)
        tgt = lax.dynamic_index_in_dim(targets, jb_c, keepdims=False)
        g_in = bwd_wire

        def norm(*out):
            # branches must agree on vma types; pvary (idempotent) unifies
            return jax.tree.map(pv, out)

        def mid_branch(_):
            _, vjp = jax.vjp(lambda p, a: stage_fn(p, a), params, xs)
            dp, dx = vjp(g_in)
            return norm(dp, _zeros_like_tree(ep), _zeros_like_tree(rp), dx,
                        jnp.zeros((), jnp.float32))

        def first_branch(_):
            _, vjp = jax.vjp(
                lambda p, e, mbx: stage_fn(p, in_proj(e, mbx)),
                params, ep, mb_b,
            )
            dp, de, _dmb = vjp(g_in)
            return norm(dp, de, _zeros_like_tree(rp),
                        jnp.zeros(wire.shape, wire.dtype),
                        jnp.zeros((), jnp.float32))

        def last_branch(_):
            lj, vjp = jax.vjp(
                lambda p, r, a: loss_fn(out_proj(r, stage_fn(p, a)), tgt),
                params, rp, xs,
            )
            dp, dr, dx = vjp(jnp.ones_like(lj))  # seed keeps lj's vma type
            return norm(dp, _zeros_like_tree(ep), dr, dx,
                        lj.astype(jnp.float32))

        role = jnp.where(me == 0, 1, jnp.where(me == n - 1, 2, 0))
        dp, de, dr, dx, lj = lax.switch(
            role, [mid_branch, first_branch, last_branch], None
        )

        def macc(acc, g):
            return jax.tree.map(
                lambda a, d: a + jnp.where(bwd_active, d, 0), acc, g
            )

        acc_p, acc_e, acc_r = macc(acc_p, dp), macc(acc_e, de), macc(acc_r, dr)
        loss_acc = loss_acc + jnp.where(bwd_active, lj, 0.0)

        fwd_wire = lax.ppermute(out, axis_name, perm_fwd)
        bwd_wire = lax.ppermute(dx, axis_name, perm_bwd)
        return (acc_p, acc_e, acc_r, act_buf, fwd_wire, bwd_wire,
                loss_acc), None

    carry0 = (
        jax.tree.map(lambda p: pv(jnp.zeros_like(p)), params),
        jax.tree.map(lambda p: pv(jnp.zeros_like(p)), ep),
        jax.tree.map(lambda p: pv(jnp.zeros_like(p)), rp),
        pv(jnp.zeros((L,) + wire.shape, wire.dtype)),
        pv(jnp.zeros(wire.shape, wire.dtype)),
        pv(jnp.zeros(wire.shape, wire.dtype)),
        pv(jnp.zeros((), jnp.float32)),
    )
    (acc_p, acc_e, acc_r, *_rest, loss_acc), _ = lax.scan(
        tick, carry0, jnp.arange(m + 2 * n - 2)
    )
    loss = lax.psum(loss_acc, axis_name) / m
    stage_grads = jax.tree.map(lambda g: g[None] / m, acc_p)
    proj_grads = (
        jax.tree.map(lambda g: lax.psum(g, axis_name) / m, acc_e),
        jax.tree.map(lambda g: lax.psum(g, axis_name) / m, acc_r),
    )
    return loss, stage_grads, proj_grads


def make_pipeline_train(stage_fn, loss_fn, mesh, pipe_axis="pipe",
                        schedule="1f1b", in_proj=None, out_proj=None,
                        x_spec=None):
    """Pipeline-parallel training step factory.

    ``stage_fn(stage_params, wire) -> wire`` runs one stage at the common
    wire width; ``in_proj(proj_params[0], microbatch) -> wire`` and
    ``out_proj(proj_params[1], wire) -> pred`` lift the equal-shape
    constraint at the model boundary (raw observations in, task outputs
    out — the wire itself keeps one shape because every stage's output
    rides the same ppermute buffer); ``loss_fn(pred, target) -> scalar``.

    ``schedule``:
      - ``"gpipe"``: forward sweep then AD backward; activation memory
        grows with the microbatch count M.
      - ``"1f1b"``: eager backward — at most ``2*stages-1`` activations
        live per stage regardless of M (see :func:`_1f1b_grads`).

    Returns ``train(stacked_params, proj_params, x, targets) ->
    (loss, (stage_grads, proj_grads))`` for ``x``/``targets`` microbatched
    ``(M, mb, ...)`` (see :func:`microbatch`); gradients are averaged over
    microbatches, i.e. M controls gradient accumulation.
    """
    if mesh.shape[pipe_axis] < 2:
        raise ValueError(
            f"pipeline needs mesh axis {pipe_axis!r} >= 2, got "
            f"{mesh.shape[pipe_axis]}"
        )
    if schedule not in ("gpipe", "1f1b"):
        raise ValueError(f"unknown schedule {schedule!r}")
    in_proj = in_proj if in_proj is not None else _identity_proj
    out_proj = out_proj if out_proj is not None else _identity_proj
    x_spec = x_spec if x_spec is not None else P()
    vary = (pipe_axis,) + tuple(
        a for axes in x_spec if axes is not None
        for a in ((axes,) if isinstance(axes, str) else axes)
    )
    common = dict(stage_fn=stage_fn, loss_fn=loss_fn, in_proj=in_proj,
                  out_proj=out_proj, axis_name=pipe_axis, vary_axes=vary)
    if schedule == "gpipe":
        fwd = shard_map(
            functools.partial(_fwd_loss, **common),
            mesh=mesh,
            in_specs=(P(pipe_axis), P(), x_spec, x_spec),
            out_specs=P(),
        )

        def train(stacked_params, proj_params, x, targets):
            loss, (gs, gp) = jax.value_and_grad(fwd, argnums=(0, 1))(
                stacked_params, proj_params, x, targets
            )
            return loss, (gs, gp)

    else:
        inner = shard_map(
            functools.partial(_1f1b_grads, **common),
            mesh=mesh,
            in_specs=(P(pipe_axis), P(), x_spec, x_spec),
            out_specs=(P(), P(pipe_axis), P()),
        )

        def train(stacked_params, proj_params, x, targets):
            loss, gs, gp = inner(stacked_params, proj_params, x, targets)
            return loss, (gs, gp)

    return train


def microbatch(batch, num_microbatches):
    """Host/device-side reshape (B, ...) -> (M, B/M, ...) for the pipeline.

    Every leaf's leading axis must split evenly — a ragged split would
    silently change the per-microbatch loss weighting, so it raises the
    same actionable shape error :func:`blendjax.btt.prefetch.put_batch`
    uses, naming the offending leaf."""
    m = int(num_microbatches)
    if m < 1:
        raise ValueError(f"num_microbatches must be >= 1, got {num_microbatches}")

    def split(x):
        b = x.shape[0]
        if b % m:
            raise ValueError(
                f"batch leaf of shape {tuple(x.shape)} not splittable into "
                f"{m} microbatches: leading axis {b} leaves remainder "
                f"{b % m}; pick batch/num_microbatches divisible "
                f"(e.g. batch {b - b % m} or {b + m - b % m})"
            )
        return x.reshape((m, b // m) + x.shape[1:])

    return jax.tree.map(split, batch)
