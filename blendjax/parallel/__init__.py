"""Mesh construction, sharding rules, and SPMD train-step builders."""

from blendjax.parallel.mesh import data_mesh, data_sharding, make_mesh, replicated
from blendjax.parallel.ring_attention import (
    full_attention,
    make_ring_attention,
    ring_attention,
    ulysses_attention,
)
from blendjax.parallel.sharding import (
    detector_rules,
    make_sharded_train_step,
    param_specs,
    shard_pytree,
)

__all__ = [
    "data_mesh",
    "data_sharding",
    "make_mesh",
    "replicated",
    "detector_rules",
    "make_sharded_train_step",
    "param_specs",
    "shard_pytree",
    "full_attention",
    "make_ring_attention",
    "ring_attention",
    "ulysses_attention",
]
