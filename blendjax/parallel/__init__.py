"""Mesh construction, sharding rules, and SPMD train-step builders."""

from blendjax.parallel.mesh import data_mesh, data_sharding, make_mesh, replicated
from blendjax.parallel.sharding import (
    detector_rules,
    make_sharded_train_step,
    param_specs,
    shard_pytree,
)

__all__ = [
    "data_mesh",
    "data_sharding",
    "make_mesh",
    "replicated",
    "detector_rules",
    "make_sharded_train_step",
    "param_specs",
    "shard_pytree",
]
