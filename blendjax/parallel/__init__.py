"""Mesh construction, sharding rules, and SPMD train-step builders."""

from blendjax.parallel.mesh import data_mesh, data_sharding, make_mesh, replicated
from blendjax.parallel.podracer import (
    FleetSet,
    SegmentFanIn,
    make_segment_loss,
)
from blendjax.parallel.pipeline import (
    make_pipeline,
    make_pipeline_train,
    microbatch,
    stack_stage_params,
    unstack_stage_params,
)
from blendjax.parallel.ring_attention import (
    full_attention,
    make_ring_attention,
    ring_attention,
    ring_flash_attention,
    ulysses_attention,
    zigzag_flash_attention,
)
from blendjax.parallel.sharding import (
    detector_rules,
    make_seqformer_train_step,
    make_sharded_train_step,
    param_specs,
    seqformer_rules,
    shard_pytree,
)

__all__ = [
    "data_mesh",
    "data_sharding",
    "make_mesh",
    "replicated",
    "FleetSet",
    "SegmentFanIn",
    "make_segment_loss",
    "detector_rules",
    "seqformer_rules",
    "make_sharded_train_step",
    "make_seqformer_train_step",
    "param_specs",
    "shard_pytree",
    "full_attention",
    "make_ring_attention",
    "ring_attention",
    "ring_flash_attention",
    "ulysses_attention",
    "zigzag_flash_attention",
    "make_pipeline",
    "make_pipeline_train",
    "microbatch",
    "stack_stage_params",
    "unstack_stage_params",
]
