"""TelemetryHub: one scrapeable aggregation point for the whole plane.

Counters (:class:`~blendjax.utils.timing.EventCounters`), stage timers
with latency histograms (:class:`~blendjax.utils.timing.StageTimer`) and
health probes live per component — per fleet, per pool, per replay
buffer, per shard process.  The hub merges them on demand into one
snapshot:

- :meth:`TelemetryHub.scrape` — a JSON-able dict with every canonical
  counter (``FLEET_EVENTS`` + ``REPLAY_EVENTS`` + ``SERVE_EVENTS`` +
  ``GATEWAY_EVENTS`` + ``WEIGHT_EVENTS`` + ``SCENARIO_EVENTS`` +
  ``HA_EVENTS`` + ``AUTOSCALE_EVENTS`` + ``PIPE_EVENTS``) and
  every canonical stage (``FEED_STAGES`` + ``REPLAY_STAGES`` +
  ``SERVE_STAGES`` + ``GATEWAY_STAGES`` + ``WEIGHT_STAGES`` +
  ``SCENARIO_STAGES`` + ``HA_STAGES`` + ``AUTOSCALE_STAGES`` +
  ``PIPE_STAGES``)
  **zero-filled** (the same
  contract ``FleetSupervisor.health()`` keeps: dashboards and tests
  need no existence checks), histograms merged across components so the
  aggregate p99 is a real quantile of the union, not a mean of means;
- :meth:`TelemetryHub.to_prometheus` — the same snapshot in Prometheus
  text-exposition format (counters + latency summaries), so any scraper
  that speaks the format ingests blendjax without an HTTP dependency;
- :meth:`TelemetryHub.serve` — an optional ZMQ REP scrape socket
  speaking plain JSON (request ``{"format": "json"|"prometheus"}``,
  reply bytes), the no-HTTP transport for cross-process scraping;
- :meth:`TelemetryHub.register_remote` — pull telemetry from another
  process (e.g. a jax-free replay shard's ``telemetry`` RPC) and merge
  it like a local component; a fetch failure is reported in the
  snapshot (``remote_errors``), never raised into the scraper.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time

from blendjax.obs.histogram import fold_stage_snapshot, stage_records

logger = logging.getLogger("blendjax")

#: Prometheus metric-name prefix for everything the hub exports.
PROM_PREFIX = "blendjax"


def _canonical_counters():
    # deferred import: blendjax.utils pulls the consumer-side stack
    # (fence -> jax), which a process that merely *imports* the obs
    # package (a Blender producer) must not pay
    from blendjax.utils import timing

    return (timing.FLEET_EVENTS + timing.REPLAY_EVENTS
            + timing.SERVE_EVENTS + timing.GATEWAY_EVENTS
            + timing.WEIGHT_EVENTS + timing.SCENARIO_EVENTS
            + timing.HA_EVENTS + timing.AUTOSCALE_EVENTS
            + timing.PIPE_EVENTS)


def _canonical_stages():
    from blendjax.utils import timing

    return (timing.FEED_STAGES + timing.REPLAY_STAGES
            + timing.SERVE_STAGES + timing.GATEWAY_STAGES
            + timing.WEIGHT_STAGES + timing.SCENARIO_STAGES
            + timing.HA_STAGES + timing.AUTOSCALE_STAGES
            + timing.PIPE_STAGES)


def _zero_stage():
    return {
        "count": 0, "total_s": 0.0, "mean_ms": 0.0,
        "p50_ms": 0.0, "p90_ms": 0.0, "p99_ms": 0.0, "max_ms": 0.0,
    }


class _Component:
    __slots__ = ("counters", "timer", "probe")

    def __init__(self, counters, timer, probe):
        self.counters = counters
        self.timer = timer
        self.probe = probe


class TelemetryHub:
    """Merge-and-serve aggregator over registered telemetry sources."""

    def __init__(self, name="blendjax"):
        self.name = name
        self._lock = threading.Lock()
        self._components = {}
        self._remotes = {}
        self._serve_thread = None
        self._serve_stop = None
        self.address = None

    # -- registration --------------------------------------------------------

    def register(self, name, *, counters=None, timer=None, probe=None):
        """Attach a local component's telemetry sources under ``name``.

        ``counters``/``timer`` merge into the aggregate; ``probe`` is an
        optional zero-arg callable (e.g. ``supervisor.health``) whose
        result rides in the component's snapshot verbatim.  Re-register
        under the same name to replace (component restarts)."""
        with self._lock:
            self._components[str(name)] = _Component(counters, timer, probe)
        return self

    def register_supervisor(self, name, supervisor):
        """Convenience: a :class:`~blendjax.btt.supervise.FleetSupervisor`
        contributes its counters, its stage timer (when it has one) and
        its ``health()`` snapshot."""
        return self.register(
            name,
            counters=supervisor.counters,
            timer=getattr(supervisor, "timer", None),
            probe=supervisor.health,
        )

    def register_remote(self, name, fetch):
        """Attach a remote process's telemetry: ``fetch()`` returns a
        dict shaped like :meth:`StageTimer.snapshot` output wrapped as
        ``{"counters": {...}, "stages": {...}}`` (the replay shard
        ``telemetry`` RPC reply).  Fetched per scrape; failures land in
        the snapshot's ``remote_errors`` instead of failing it."""
        with self._lock:
            self._remotes[str(name)] = fetch
        return self

    def unregister(self, name):
        with self._lock:
            self._components.pop(str(name), None)
            self._remotes.pop(str(name), None)

    # -- aggregation ---------------------------------------------------------

    def scrape(self):
        """One merged snapshot (see module docstring for the zero-fill
        contract)."""
        with self._lock:
            components = dict(self._components)
            remotes = dict(self._remotes)
        counters = dict.fromkeys(_canonical_counters(), 0)
        merged = {}  # the fold_stage_snapshot accumulator
        comp_out = {}
        remote_errors = {}

        def fold_counters(snap):
            for k, v in (snap or {}).items():
                counters[k] = counters.get(k, 0) + int(v)

        for name, comp in components.items():
            detail = {}
            if comp.counters is not None:
                snap = comp.counters.snapshot()
                detail["counters"] = snap
                fold_counters(snap)
            if comp.timer is not None:
                # one snapshot serves both the aggregate fold and the
                # per-component records (no second lock acquisition /
                # quantile recomputation via summary())
                stages_snap = comp.timer.snapshot()
                fold_stage_snapshot(merged, stages_snap)
                detail["stages"] = stage_records(
                    fold_stage_snapshot({}, stages_snap)
                )
            if comp.probe is not None:
                try:
                    detail["probe"] = comp.probe()
                except Exception as exc:  # noqa: BLE001 - scrape survives
                    detail["probe_error"] = f"{type(exc).__name__}: {exc}"
            comp_out[name] = detail
        for name, fetch in remotes.items():
            try:
                snap = fetch()
            except Exception as exc:  # noqa: BLE001 - scrape survives
                remote_errors[name] = f"{type(exc).__name__}: {exc}"
                continue
            fold_counters(snap.get("counters"))
            fold_stage_snapshot(merged, snap.get("stages"))
            comp_out[name] = {
                k: v for k, v in snap.items() if k not in ("stages",)
            }
        stages = {}
        for stage in _canonical_stages():
            stages[stage] = _zero_stage()
        stages.update(stage_records(merged))
        out = {
            "hub": self.name,
            "ts": time.time(),
            "pid": os.getpid(),
            "counters": counters,
            "stages": stages,
            "components": comp_out,
        }
        if remote_errors:
            out["remote_errors"] = remote_errors
        return out

    # -- prometheus ----------------------------------------------------------

    def to_prometheus(self, snapshot=None):
        """The scrape in Prometheus text-exposition format (0.0.4):
        counters as ``<prefix>_events_total`` and stage latencies as
        quantile summaries."""
        snap = snapshot or self.scrape()
        lines = [
            f"# HELP {PROM_PREFIX}_events_total "
            "Fleet/replay fault and lifecycle event counts.",
            f"# TYPE {PROM_PREFIX}_events_total counter",
        ]
        for event in sorted(snap["counters"]):
            lines.append(
                f'{PROM_PREFIX}_events_total{{event="{event}"}} '
                f'{int(snap["counters"][event])}'
            )
        metric = f"{PROM_PREFIX}_stage_latency_seconds"
        lines += [
            f"# HELP {metric} Per-stage latency quantiles.",
            f"# TYPE {metric} summary",
        ]
        for stage in sorted(snap["stages"]):
            rec = snap["stages"][stage]
            for q, key in (("0.5", "p50_ms"), ("0.9", "p90_ms"),
                           ("0.99", "p99_ms")):
                lines.append(
                    f'{metric}{{stage="{stage}",quantile="{q}"}} '
                    f'{rec[key] / 1e3:.9g}'
                )
            lines.append(
                f'{metric}_sum{{stage="{stage}"}} {rec["total_s"]:.9g}'
            )
            lines.append(
                f'{metric}_count{{stage="{stage}"}} {int(rec["count"])}'
            )
        lines += [
            f"# HELP {metric}_max Per-stage maximum observed latency.",
            f"# TYPE {metric}_max gauge",
        ]
        for stage in sorted(snap["stages"]):
            lines.append(
                f'{metric}_max{{stage="{stage}"}} '
                f'{snap["stages"][stage]["max_ms"] / 1e3:.9g}'
            )
        return "\n".join(lines) + "\n"

    # -- ZMQ scrape socket ---------------------------------------------------

    def serve(self, address="tcp://127.0.0.1:*"):
        """Serve scrapes on a ZMQ REP socket from a daemon thread — the
        no-HTTP-dependency exposition transport.  Protocol: the request
        is JSON bytes (``{}`` or ``{"format": "json"|"prometheus"}``;
        malformed/empty requests default to JSON), the reply is UTF-8
        JSON or Prometheus text bytes.  Returns the bound address
        (``:*`` binds an ephemeral port).  One server per hub."""
        import zmq

        if self._serve_thread is not None:
            raise RuntimeError("hub scrape socket already serving")
        ctx = zmq.Context.instance()
        sock = ctx.socket(zmq.REP)
        sock.setsockopt(zmq.LINGER, 0)
        if address.endswith(":*") or address.endswith(":0"):
            base = address.rsplit(":", 1)[0]
            port = sock.bind_to_random_port(base)
            self.address = f"{base}:{port}"
        else:
            sock.bind(address)
            self.address = address
        stop = threading.Event()

        def loop():
            try:
                while not stop.is_set():
                    if not sock.poll(100, zmq.POLLIN):
                        continue
                    raw = sock.recv()
                    fmt = "json"
                    try:
                        req = json.loads(raw) if raw else {}
                        if isinstance(req, dict):
                            fmt = req.get("format", "json")
                    except ValueError:
                        pass
                    try:
                        if fmt == "prometheus":
                            body = self.to_prometheus().encode()
                        else:
                            body = json.dumps(
                                self.scrape(), default=repr
                            ).encode()
                    except Exception as exc:  # noqa: BLE001
                        logger.exception("hub scrape failed")
                        body = json.dumps(
                            {"error": f"{type(exc).__name__}: {exc}"}
                        ).encode()
                    sock.send(body)
            except zmq.ZMQError:
                pass  # socket closed under us: clean shutdown
            finally:
                sock.close(0)

        self._serve_stop = stop
        self._serve_thread = threading.Thread(
            target=loop, daemon=True, name="bjx-telemetry-hub"
        )
        self._serve_thread.start()
        return self.address

    def close(self):
        if self._serve_thread is not None:
            self._serve_stop.set()
            self._serve_thread.join(timeout=5)
            self._serve_thread = None
            self._serve_stop = None
            self.address = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


def scrape_socket(address, fmt="json", timeout_ms=2000):
    """One scrape from a hub's REP socket (see :meth:`TelemetryHub.serve`).
    Returns the parsed dict for ``fmt="json"`` and the exposition text
    for ``fmt="prometheus"``; raises TimeoutError when nothing answers."""
    import zmq

    ctx = zmq.Context.instance()
    sock = ctx.socket(zmq.REQ)
    sock.setsockopt(zmq.LINGER, 0)
    try:
        sock.connect(address)
        sock.send(json.dumps({"format": fmt}).encode())
        if not sock.poll(timeout_ms, zmq.POLLIN):
            raise TimeoutError(
                f"no scrape reply from {address} within {timeout_ms} ms"
            )
        body = sock.recv()
        if fmt == "prometheus":
            return body.decode()
        return json.loads(body)
    finally:
        sock.close(0)
