"""Cross-process trace spans over the existing wire correlation ids.

The feed/RL pipelines already correlate every request/reply pair with a
``wire.BTMID_KEY`` id; this module turns that id into a **trace id** so
one env step's producer render, wire transit, arena scatter and learner
compute appear as one nested timeline across processes:

- a *client* (``EnvPool``, ``ShardClient``) stamps its request with a
  span context (``wire.SPAN_KEY``) and records a client-side span for
  the whole RPC, tagged with the correlation id;
- a *server* (``RemoteControlledAgent``, ``ReplayShard``) that sees the
  span context records its own recv->work->reply span and ships it back
  **piggybacked on the reply** (``wire.SPANS_KEY``) — no extra sockets,
  and jax-free shard/producer processes need no exporter of their own;
- the client ingests piggybacked spans into its
  :class:`SpanRecorder`, so ONE :func:`export_chrome_trace` call emits a
  single Perfetto/chrome-tracing JSON where spans from every pid share
  a timeline.

Timestamps are **wall-clock epoch microseconds** (``time.time_ns``), not
process-relative ``perf_counter`` values, so spans recorded in different
processes on one host align without clock negotiation.  (Cross-HOST
merging would need NTP-grade clocks; same-host is the deployment today.)

Pure stdlib: producers run inside Blender's embedded Python and shard
processes are deliberately jax/numpy-free on their fast path.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from contextlib import contextmanager


def now_us():
    """Wall-clock epoch microseconds (the shared span timebase)."""
    return time.time_ns() // 1000


def make_span(name, t0_us, *, dur_us=None, trace=None, cat=None,
              pid=None, tid=None, args=None):
    """One chrome-tracing complete event (``ph: "X"``).  ``dur_us=None``
    closes the span now."""
    span = {
        "name": name,
        "ph": "X",
        "ts": t0_us,
        "dur": (now_us() - t0_us) if dur_us is None else dur_us,
        "pid": os.getpid() if pid is None else pid,
        "tid": threading.get_ident() if tid is None else tid,
    }
    if cat is not None:
        span["cat"] = cat
    a = dict(args) if args else {}
    if trace is not None:
        a["trace"] = trace
    if a:
        span["args"] = a
    return span


def span_trace(span):
    """The trace (correlation) id a span was tagged with, or None."""
    return (span.get("args") or {}).get("trace")


class SpanRecorder:
    """Thread-safe bounded ring of completed spans.

    Bounded for the same reason the StageTimer trace ring is: a
    multi-hour traced run must not exhaust host memory.  Overflow drops
    the OLDEST spans (the recent window is what a postmortem wants) and
    counts them in :attr:`dropped`.
    """

    def __init__(self, capacity=8192):
        self._lock = threading.Lock()
        self._spans = deque(maxlen=int(capacity))
        self._dropped = 0

    @property
    def capacity(self):
        return self._spans.maxlen

    @property
    def dropped(self):
        with self._lock:
            return self._dropped

    def __len__(self):
        with self._lock:
            return len(self._spans)

    def record(self, span):
        """Append one span dict (see :func:`make_span`)."""
        with self._lock:
            if len(self._spans) == self._spans.maxlen:
                self._dropped += 1
            self._spans.append(span)

    @contextmanager
    def span(self, name, *, trace=None, cat=None, args=None):
        """Record the ``with`` block as one span."""
        t0 = now_us()
        try:
            yield
        finally:
            self.record(
                make_span(name, t0, trace=trace, cat=cat, args=args)
            )

    def ingest(self, spans):
        """Absorb spans shipped back by a remote peer (a reply's
        ``wire.SPANS_KEY`` list).  Tolerant of None/[] so reply handling
        can pop-and-ingest unconditionally."""
        if not spans:
            return 0
        with self._lock:
            for s in spans:
                if isinstance(s, dict):
                    if len(self._spans) == self._spans.maxlen:
                        self._dropped += 1
                    self._spans.append(s)
        return len(spans)

    def snapshot(self):
        with self._lock:
            return list(self._spans)

    def drain(self):
        """Pop every recorded span (the PUSH-to-hub consumption mode)."""
        with self._lock:
            out = list(self._spans)
            self._spans.clear()
            return out

    def export_chrome_trace(self, path, extra=()):
        """Write this recorder's spans (plus ``extra`` span iterables)
        as one chrome-tracing JSON; returns the event count."""
        return export_chrome_trace(path, self.snapshot(), *extra)


def export_chrome_trace(path, *span_sources):
    """Merge span iterables / :class:`SpanRecorder` instances /
    previously-exported trace file paths into ONE chrome-tracing JSON at
    ``path`` (loadable in Perfetto / ``chrome://tracing``; each pid gets
    its own process row).  Events are sorted by timestamp so the
    timeline reads consistently whatever order sources arrived in.
    Returns the number of events written."""
    events = []
    for src in span_sources:
        if src is None:
            continue
        if isinstance(src, SpanRecorder):
            events.extend(src.snapshot())
        elif isinstance(src, (str, os.PathLike)):
            events.extend(load_chrome_trace(src))
        else:
            events.extend(src)
    events.sort(key=lambda e: (e.get("ts", 0), e.get("pid", 0)))
    with open(path, "w") as f:
        json.dump({"traceEvents": events, "displayTimeUnit": "ms"}, f)
    return len(events)


def load_chrome_trace(path):
    """Events of a chrome-tracing JSON file (for re-merging exports from
    several processes into one timeline)."""
    with open(path) as f:
        doc = json.load(f)
    if isinstance(doc, dict):
        return list(doc.get("traceEvents", []))
    return list(doc)  # bare event-array form is also valid chrome JSON
