"""Fixed-memory log-bucketed latency histograms.

Mean-only stage timings hide exactly the number the serving tier is
specified in: the tail (ROADMAP #3 is a ``serve_p99_ms`` target, and
Podracer-style pipelines stall at the p99 of their slowest stage, not
the mean).  :class:`LatencyHistogram` records durations into
HdrHistogram-style buckets — one power-of-two octave split into
``2**SUBBITS`` linear sub-buckets — so memory is fixed (a few hundred
ints, no per-event allocation), recording is O(1) with no syscalls, and
any quantile is recoverable within the bucket's relative width
(<= 1/2**SUBBITS, i.e. <= 12.5% at the default 8 sub-buckets) for any
distribution.

Pure stdlib on purpose: histograms ride inside
:class:`blendjax.utils.timing.StageTimer` on the feed hot path, travel
over the wire in :meth:`to_dict` form (replay shard ``telemetry`` RPCs),
and are merged across processes by the
:class:`~blendjax.obs.hub.TelemetryHub` — none of which may pull numpy
or jax into a producer/shard process.

Not thread-safe by itself: every writer (``StageTimer``) already holds
its own lock around recording, and readers consume :meth:`to_dict`
snapshots taken under that lock.
"""

from __future__ import annotations

import math

#: Sub-bucket resolution: each power-of-two octave is split into
#: ``2**SUBBITS`` linear sub-buckets, bounding any quantile's relative
#: error by half the bucket width (~6% at 3 bits).
SUBBITS = 3
_SUB = 1 << SUBBITS

#: Octaves covered above the 1 us floor: bucket ranges reach
#: ``2**OCTAVES`` us (~2147 s); slower events clamp into the top bucket
#: (their exact maximum is still tracked separately).
OCTAVES = 31

#: Total bucket count: one underflow bucket (< 1 us) + the octave grid.
NBUCKETS = 1 + OCTAVES * _SUB


def bucket_index(seconds):
    """Bucket index for a duration (clamped into [0, NBUCKETS))."""
    us = seconds * 1e6
    if us < 1.0:
        return 0
    m, e = math.frexp(us)  # us = m * 2**e with m in [0.5, 1)
    idx = ((e - 1) << SUBBITS) + int((m + m - 1.0) * _SUB) + 1
    return idx if idx < NBUCKETS else NBUCKETS - 1


def bucket_bounds(idx):
    """``(lo_s, hi_s)`` duration range of bucket ``idx``."""
    if idx <= 0:
        return 0.0, 1e-6
    o, sub = (idx - 1) >> SUBBITS, (idx - 1) & (_SUB - 1)
    base = float(1 << o)
    return (
        base * (1.0 + sub / _SUB) * 1e-6,
        base * (1.0 + (sub + 1) / _SUB) * 1e-6,
    )


class LatencyHistogram:
    """Fixed-size log-bucketed duration histogram (seconds in,
    p50/p90/p99/max out)."""

    __slots__ = ("counts", "n", "sum_s", "max_s")

    def __init__(self):
        self.counts = [0] * NBUCKETS
        self.n = 0
        self.sum_s = 0.0
        self.max_s = 0.0

    def add(self, seconds, _frexp=math.frexp, _top=NBUCKETS - 1):
        # bucket_index inlined: this runs on the feed hot path under
        # StageTimer's lock, priced by telemetry_overhead_x every bench
        us = seconds * 1e6
        if us < 1.0:
            idx = 0
        else:
            m, e = _frexp(us)
            idx = ((e - 1) << SUBBITS) + int((m + m - 1.0) * _SUB) + 1
            if idx > _top:
                idx = _top
        self.counts[idx] += 1
        self.n += 1
        self.sum_s += seconds
        if seconds > self.max_s:
            self.max_s = seconds

    def add_many(self, seconds, k):
        """``k`` events at the same duration in one update (the
        ``add_bulk`` fast path: pre-aggregated intervals carry only
        their mean, so the bucket resolution is the mean's)."""
        self.counts[bucket_index(seconds)] += k
        self.n += k
        self.sum_s += seconds * k
        if seconds > self.max_s:
            self.max_s = seconds

    def merge(self, other):
        """Fold ``other``'s counts into this histogram (cross-thread /
        cross-process aggregation; buckets are position-aligned by
        construction)."""
        counts = self.counts
        for i, c in enumerate(other.counts):
            if c:
                counts[i] += c
        self.n += other.n
        self.sum_s += other.sum_s
        if other.max_s > self.max_s:
            self.max_s = other.max_s
        return self

    def quantile(self, q):
        """The ``q``-quantile duration in seconds (bucket-midpoint
        estimate, clamped to the exact observed maximum; 0.0 while
        empty).  Upper-rank convention — the bucket of the
        ``(floor(q*n)+1)``-th smallest event — so a q landing exactly on
        a mode boundary reports the slow side (the side a latency SLO
        cares about)."""
        if self.n <= 0:
            return 0.0
        rank = q * self.n
        seen = 0
        for idx, c in enumerate(self.counts):
            if not c:
                continue
            seen += c
            if seen > rank:
                lo, hi = bucket_bounds(idx)
                return min((lo + hi) / 2.0, self.max_s)
        return self.max_s

    def percentiles(self):
        """``{"p50_ms", "p90_ms", "p99_ms", "max_ms"}`` — the shared
        reporting shape (summary(), health(), scrape(), bench
        artifacts)."""
        return {
            "p50_ms": round(self.quantile(0.50) * 1e3, 4),
            "p90_ms": round(self.quantile(0.90) * 1e3, 4),
            "p99_ms": round(self.quantile(0.99) * 1e3, 4),
            "max_ms": round(self.max_s * 1e3, 4),
        }

    # -- wire form -----------------------------------------------------------

    def to_dict(self):
        """Sparse JSON-able snapshot (non-zero buckets only) — the form
        shard ``telemetry`` RPC replies and hub merges travel in."""
        return {
            "n": self.n,
            "sum_s": self.sum_s,
            "max_s": self.max_s,
            "counts": {
                str(i): c for i, c in enumerate(self.counts) if c
            },
        }

    @classmethod
    def from_dict(cls, d):
        h = cls()
        if not d:
            return h
        h.n = int(d.get("n", 0))
        h.sum_s = float(d.get("sum_s", 0.0))
        h.max_s = float(d.get("max_s", 0.0))
        for i, c in (d.get("counts") or {}).items():
            h.counts[int(i)] = int(c)
        return h

    def copy(self):
        h = LatencyHistogram()
        h.counts = list(self.counts)
        h.n, h.sum_s, h.max_s = self.n, self.sum_s, self.max_s
        return h


# ---------------------------------------------------------------------------
# stage-snapshot merging (shared by TelemetryHub.scrape and
# supervise.aggregate_health — ONE implementation of the fold so the
# merge semantics cannot drift between the two surfaces)
# ---------------------------------------------------------------------------


def fold_stage_snapshot(merged, snapshot):
    """Fold one ``StageTimer.snapshot()``-shaped dict into ``merged``
    (``{stage: [count, total_s, LatencyHistogram | None]}``).

    Histograms may arrive as live objects (local timers hand out
    copies) or serialized dicts (remote ``telemetry`` RPC replies);
    the fold takes ownership and merges destructively.
    """
    for stage, rec in (snapshot or {}).items():
        slot = merged.setdefault(stage, [0, 0.0, None])
        slot[0] += int(rec.get("count", 0))
        slot[1] += float(rec.get("total_s", 0.0))
        hist = rec.get("hist")
        if hist is not None:
            if not isinstance(hist, LatencyHistogram):
                hist = LatencyHistogram.from_dict(hist)
            slot[2] = hist if slot[2] is None else slot[2].merge(hist)
    return merged


def stage_records(merged):
    """Render a :func:`fold_stage_snapshot` accumulator as reporting
    records: ``{stage: {"count", "total_s", "mean_ms", "p50_ms",
    "p90_ms", "p99_ms", "max_ms"}}`` (percentiles zero when no
    histogram contributed)."""
    out = {}
    for stage, (count, total_s, hist) in merged.items():
        rec = {
            "count": count,
            "total_s": round(total_s, 6),
            "mean_ms": round((total_s / count) * 1e3, 4) if count else 0.0,
        }
        rec.update(
            hist.percentiles() if hist is not None
            else {"p50_ms": 0.0, "p90_ms": 0.0, "p99_ms": 0.0,
                  "max_ms": 0.0}
        )
        out[stage] = rec
    return out
