"""blendjax.obs — the unified telemetry plane (see docs/observability.md).

Four pieces, all wire-friendly and jax/numpy-free so producer (Blender)
and shard processes can carry them on their fast paths:

- :class:`~blendjax.obs.histogram.LatencyHistogram` — fixed-memory
  log-bucketed latency histograms, folded into
  :class:`blendjax.utils.timing.StageTimer` so every canonical stage
  reports p50/p90/p99/max, not just means;
- :mod:`~blendjax.obs.spans` — cross-process trace spans riding the
  existing ``wire.BTMID_KEY`` correlation ids, piggybacked on replies
  and merged into one Perfetto/chrome-tracing timeline;
- :class:`~blendjax.obs.hub.TelemetryHub` — a scrapeable aggregator
  (JSON + Prometheus text exposition, optional ZMQ REP scrape socket)
  merging counters and histograms across components and processes;
- :class:`~blendjax.obs.flight.FlightRecorder` — a bounded ring of
  recent annotated fault events, dumped as a postmortem JSON on
  quarantine escalation or process death.

Import-light on purpose (PEP 562, like :mod:`blendjax` itself):
producers inside Blender's embedded Python import
``blendjax.obs.spans`` without dragging in the hub's consumer-side
dependency chain.
"""

_EXPORTS = {
    "LatencyHistogram": ("blendjax.obs.histogram", "LatencyHistogram"),
    "SpanRecorder": ("blendjax.obs.spans", "SpanRecorder"),
    "export_chrome_trace": ("blendjax.obs.spans", "export_chrome_trace"),
    "load_chrome_trace": ("blendjax.obs.spans", "load_chrome_trace"),
    "make_span": ("blendjax.obs.spans", "make_span"),
    "now_us": ("blendjax.obs.spans", "now_us"),
    "span_trace": ("blendjax.obs.spans", "span_trace"),
    "TelemetryHub": ("blendjax.obs.hub", "TelemetryHub"),
    "scrape_socket": ("blendjax.obs.hub", "scrape_socket"),
    "FlightRecorder": ("blendjax.obs.flight", "FlightRecorder"),
    "flight_recorder": ("blendjax.obs.flight", "flight_recorder"),
    "default_postmortem_dir": (
        "blendjax.obs.flight", "default_postmortem_dir",
    ),
}


def __getattr__(name):
    try:
        module, attr = _EXPORTS[name]
    except KeyError:
        raise AttributeError(
            f"module 'blendjax.obs' has no attribute {name!r}"
        ) from None
    import importlib

    value = getattr(importlib.import_module(module), attr)
    globals()[name] = value
    return value


def __dir__():
    return sorted(list(globals()) + list(_EXPORTS))
