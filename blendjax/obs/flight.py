"""Flight recorders: the last N annotated events, dumpable post-crash.

Counters say HOW MANY quarantines happened; after a chaos failure or a
production incident the question is WHICH target, WHEN, in WHAT order
relative to the retries and circuit trips around it.  A
:class:`FlightRecorder` keeps a bounded ring of recent annotated events
(quarantines, retries, circuit opens, shard RPC failures, deaths — with
wall-clock timestamps and payload digests) that costs nothing until
something goes wrong, and :meth:`FlightRecorder.dump` writes the ring as
a postmortem JSON the moment it does (supervisor death handling and
quarantine escalation call it; ``BJX_POSTMORTEM_DIR`` names the default
destination so chaos runs produce diagnosable artifacts without
plumbing).

A process-wide default instance (:data:`flight_recorder`) is shared by
the fault layer the same way ``fleet_counters`` is — events land there
without constructor plumbing; components that need isolated rings take
a ``flight=`` override.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import threading
import time
from collections import deque

logger = logging.getLogger("blendjax")

#: Environment variable naming the default postmortem output directory.
#: ``make chaos`` / ``make chaos-replay`` set it so every chaos failure
#: leaves a postmortem artifact; unset, dumps without an explicit path
#: are skipped (library code must not scatter files by default).
POSTMORTEM_DIR_ENV = "BJX_POSTMORTEM_DIR"


def default_postmortem_dir():
    """The ``BJX_POSTMORTEM_DIR`` directory, or None when unset."""
    return os.environ.get(POSTMORTEM_DIR_ENV) or None


def _digest(payload):
    """Short stable digest of an event's details — lets two postmortems
    (or a postmortem and a log line) be matched without shipping the
    full payload twice."""
    blob = json.dumps(payload, sort_keys=True, default=repr)
    return hashlib.sha1(blob.encode()).hexdigest()[:12]


class FlightRecorder:
    """Thread-safe bounded ring of annotated events.

    Recording is cheap (one lock + dict append) and the ring is bounded,
    so hooks may fire on every fault-layer event of a multi-hour run;
    overflow drops the oldest events and counts them.
    """

    def __init__(self, capacity=512):
        self._lock = threading.Lock()
        self._events = deque(maxlen=int(capacity))
        self._dropped = 0

    @property
    def dropped(self):
        with self._lock:
            return self._dropped

    def __len__(self):
        with self._lock:
            return len(self._events)

    def note(self, event, target=None, **details):
        """Record one event (``target`` names what it happened to, e.g.
        ``"env3"`` / ``"shard1"`` / ``"fleet0"``)."""
        rec = {
            "ts": time.time(),
            "event": str(event),
            "target": None if target is None else str(target),
            "details": {k: v for k, v in details.items() if v is not None},
        }
        rec["digest"] = _digest(rec["details"])
        with self._lock:
            if len(self._events) == self._events.maxlen:
                self._dropped += 1
            self._events.append(rec)
        return rec

    def snapshot(self):
        with self._lock:
            return list(self._events)

    def clear(self):
        with self._lock:
            self._events.clear()
            self._dropped = 0

    def dump(self, path=None, *, reason="", extra=None, directory=None):
        """Write the ring as a postmortem JSON; returns the path, or
        None when no destination is known (no ``path``, no
        ``directory``, no ``BJX_POSTMORTEM_DIR``).

        Never raises: the dump runs on failure paths (supervisor death
        callbacks, quarantine escalation) where a secondary I/O error
        must not mask the original fault.
        """
        try:
            if path is None:
                directory = directory or default_postmortem_dir()
                if directory is None:
                    return None
                os.makedirs(directory, exist_ok=True)
                slug = "".join(
                    c if c.isalnum() else "-" for c in str(reason)
                )[:48].strip("-") or "event"
                path = os.path.join(
                    directory,
                    f"postmortem-{int(time.time() * 1e3)}"
                    f"-pid{os.getpid()}-{slug}.json",
                )
            with self._lock:
                events = list(self._events)
                dropped = self._dropped
            doc = {
                "format": "blendjax.postmortem/1",
                "ts": time.time(),
                "pid": os.getpid(),
                "reason": str(reason),
                "events": events,
                "events_dropped": dropped,
            }
            if extra:
                doc["extra"] = extra
            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump(doc, f, indent=1, default=repr)
            os.replace(tmp, path)
            logger.warning("flight recorder postmortem written: %s", path)
            return path
        except Exception:  # noqa: BLE001 - diagnostics must not cascade
            logger.exception("flight recorder dump failed")
            return None


#: Process-wide default ring (fault layer, quarantine paths, supervisor
#: death handling) — the flight analog of ``timing.fleet_counters``.
flight_recorder = FlightRecorder()
