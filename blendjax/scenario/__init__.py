"""Scenario plane: catalog, live domain randomization, curriculum.

The workload-diversity axis (ROADMAP #5): named
:class:`ScenarioSpec`/:class:`ScenarioCatalog` scene configs with
seeded sampling and JSON round-trip, a :class:`DomainRandomizer` that
pushes sampled params into RUNNING producers over the duplex control
plane (the densityopt pattern), and a :class:`CurriculumScheduler`
that reweights the fleet's scenario mix from per-scenario replay
strata.  Scenario ids ride in-band on transitions (the ``healthy``-key
pattern), so replay rows, telemetry and serve traffic all attribute to
scenarios.  See docs/scenarios.md.

Import-light on purpose (numpy + zmq lazily via the duplex channel):
usable from producer-side scripts and jax-free processes alike.
"""

from blendjax.scenario.catalog import (  # noqa: F401
    CATALOG_FORMAT,
    ScenarioCatalog,
    ScenarioSpec,
)
from blendjax.scenario.curriculum import (  # noqa: F401
    POLICIES,
    CurriculumScheduler,
    apportion,
)
from blendjax.scenario.randomize import (  # noqa: F401
    PUSH_CMD,
    DomainRandomizer,
)

__all__ = [
    "CATALOG_FORMAT",
    "POLICIES",
    "PUSH_CMD",
    "CurriculumScheduler",
    "DomainRandomizer",
    "ScenarioCatalog",
    "ScenarioSpec",
    "apportion",
]
