"""Curriculum scheduler: reweights the fleet's scenario mix on an
interval from per-scenario replay evidence.

Three policies, in escalating opinionation (docs/scenarios.md):

- ``uniform`` — every scenario carries equal weight forever; the mix
  never changes, and the replay draw stream is byte-identical to a
  scenario-less run (the scenario plane's no-op contract);
- ``prioritized`` — weight follows per-scenario TD-priority evidence
  scraped from the replay strata
  (:meth:`blendjax.replay.ReplayBuffer.scenario_stats`): scenarios
  whose rows carry larger error magnitudes (``priority_mass`` per
  eligible row) get more fleets — the classic "train where the model
  is worst" curriculum, smoothed by ``temperature`` and floored by
  ``floor`` so no scenario starves;
- ``pinned`` — a hand-set weight dict (:meth:`pin`); operator
  override, also the deterministic shift a curriculum test pins.

The scheduler only DECIDES: :meth:`tick` (interval-gated) returns the
fresh mix when it changed, and :meth:`assign` apportions a mix over N
fleets (largest-remainder, catalog order — deterministic).  Driving
the assignment into producers is the
:class:`~blendjax.scenario.randomize.DomainRandomizer`'s job, and the
:class:`~blendjax.models.actor_learner.ActorLearner` wires the two
together (``scenarios=``/``curriculum=``).
"""

from __future__ import annotations

import threading
import time

from blendjax.utils.timing import StageTimer, fleet_counters

POLICIES = ("uniform", "prioritized", "pinned")


def _normalize(weights, floor=0.0):
    """Floor + renormalize a name->weight dict (floor applied as a
    minimum share AFTER normalization, then renormalized once more)."""
    names = list(weights)
    total = sum(max(0.0, float(weights[n])) for n in names)
    if total <= 0:
        return {n: 1.0 / len(names) for n in names}
    out = {n: max(0.0, float(weights[n])) / total for n in names}
    if floor > 0:
        out = {n: max(floor, w) for n, w in out.items()}
        total = sum(out.values())
        out = {n: w / total for n, w in out.items()}
    return out


def apportion(mix, n):
    """Largest-remainder apportionment of ``n`` fleets over a
    name->weight mix, deterministic: quotas floor first, remainders
    break ties by mix order.  Every returned list has length ``n``."""
    names = list(mix)
    if not names:
        raise ValueError("cannot apportion an empty mix")
    weights = _normalize({k: mix[k] for k in names})
    quotas = [(name, weights[name] * n) for name in names]
    counts = {name: int(q) for name, q in quotas}
    left = n - sum(counts.values())
    # largest remainder first; ties fall back to mix order (index)
    order = sorted(
        range(len(quotas)),
        key=lambda i: (-(quotas[i][1] - int(quotas[i][1])), i),
    )
    for i in order[:left]:
        counts[quotas[i][0]] += 1
    out = []
    for name in names:
        out.extend([name] * counts[name])
    return out


class CurriculumScheduler:
    """Interval-gated scenario-mix policy (module docstring).

    Params
    ------
    scenarios: ScenarioCatalog | sequence[str]
        The scenario names the mix spans (catalog order is canonical).
    policy: "uniform" | "prioritized" | "pinned"
        Starting policy; :meth:`pin` switches to ``pinned`` live.
    interval: int
        Learner updates between reweight passes (:meth:`tick` counts
        its own calls; the ActorLearner calls it once per update).
    temperature: float
        Exponent on the prioritized evidence (1 = proportional;
        higher sharpens toward the hardest scenario).
    floor: float
        Minimum post-normalization share per scenario (prevents
        starvation; must satisfy ``floor * len(scenarios) <= 1``).
    ema: float
        Smoothing factor on per-scenario return observations
        (:meth:`observe_return`), kept for reporting and available to
        custom policies.
    counters / timer:
        ``SCENARIO_EVENTS`` sink / ``SCENARIO_STAGES`` timer.
    """

    def __init__(self, scenarios, *, policy="uniform", interval=8,
                 temperature=1.0, floor=0.05, ema=0.2,
                 counters=None, timer=None):
        names = (scenarios.names() if hasattr(scenarios, "names")
                 else list(scenarios))
        if not names:
            raise ValueError("curriculum needs at least one scenario")
        if policy not in POLICIES:
            raise ValueError(
                f"unknown curriculum policy {policy!r}; one of {POLICIES}"
            )
        if floor * len(names) > 1.0 + 1e-9:
            raise ValueError(
                f"floor={floor} over {len(names)} scenarios exceeds "
                "total mass 1.0"
            )
        self.names = names
        self.policy = policy
        self.interval = max(1, int(interval))
        self.temperature = float(temperature)
        self.floor = float(floor)
        self.ema = float(ema)
        self.counters = counters if counters is not None else fleet_counters
        self.timer = timer if timer is not None else StageTimer()
        self._lock = threading.Lock()
        self._mix = {n: 1.0 / len(names) for n in names}
        self._pinned = None
        self._returns = {}   # scenario -> EMA return
        self._ticks = 0
        self._updates = 0
        self._changes = 0

    # -- evidence ------------------------------------------------------------

    def observe_return(self, scenario, value):
        """Fold one per-scenario segment return into the EMA record
        (reporting surface; the prioritized policy reads replay
        priorities, which subsume returns as a difficulty signal)."""
        if scenario is None or scenario not in self.names:
            return
        with self._lock:
            prev = self._returns.get(scenario)
            self._returns[scenario] = (
                float(value) if prev is None
                else (1 - self.ema) * prev + self.ema * float(value)
            )

    def pin(self, weights):
        """Hand-pin the mix (operator override): switches the policy to
        ``pinned``; the next reweight pass applies it."""
        unknown = sorted(set(weights) - set(self.names))
        if unknown:
            raise ValueError(
                f"pinned mix names unknown scenario(s) {unknown}; "
                f"known: {self.names}"
            )
        with self._lock:
            self._pinned = _normalize(
                {n: float(weights.get(n, 0.0)) for n in self.names}
            )
            self.policy = "pinned"

    # -- decision ------------------------------------------------------------

    def mix(self):
        """The current name->weight mix (normalized)."""
        with self._lock:
            return dict(self._mix)

    def replay_mix(self):
        """The mix to shape replay draws with, or None when the mix is
        uniform — the scenario-less identity, so a uniform curriculum
        provably cannot perturb the draw stream
        (:meth:`blendjax.replay.ReplayBuffer.sample`'s contract)."""
        mix = self.mix()
        vals = list(mix.values())
        if max(vals) - min(vals) < 1e-12:
            return None
        return mix

    def update(self, scenario_stats=None):
        """One reweight pass (NOT interval-gated — :meth:`tick` is):
        computes the policy's fresh mix from ``scenario_stats`` (the
        :meth:`ReplayBuffer.scenario_stats` shape) and returns it.
        Counts ``scenario_curriculum_updates`` always and
        ``scenario_mix_changes`` when the mix moved."""
        t0 = time.perf_counter()
        with self._lock:
            if self.policy == "pinned" and self._pinned is not None:
                fresh = dict(self._pinned)
            elif self.policy == "prioritized" and scenario_stats:
                evidence = {}
                for n in self.names:
                    rec = scenario_stats.get(n)
                    if rec and rec.get("eligible"):
                        mean_p = (
                            float(rec.get("priority_mass", 0.0))
                            / max(int(rec["eligible"]), 1)
                        )
                        evidence[n] = max(mean_p, 0.0) ** self.temperature
                    else:
                        # no evidence yet: ride the current share so an
                        # unsampled scenario is not zeroed out
                        evidence[n] = self._mix[n]
                fresh = _normalize(evidence, floor=self.floor)
            else:
                # uniform (or prioritized with no evidence at all)
                fresh = {n: 1.0 / len(self.names) for n in self.names}
            changed = any(
                abs(fresh[n] - self._mix[n]) > 1e-9 for n in self.names
            )
            self._mix = fresh
            self._updates += 1
            if changed:
                self._changes += 1
        self.counters.incr("scenario_curriculum_updates")
        if changed:
            self.counters.incr("scenario_mix_changes")
        self.timer.add("scenario_reweight", time.perf_counter() - t0,
                       _t0=t0)
        return dict(fresh)

    def tick(self, scenario_stats_fn=None):
        """Interval gate: every ``interval``-th call runs
        :meth:`update` (fetching stats via ``scenario_stats_fn``) and
        returns the fresh mix; other calls return None."""
        with self._lock:
            self._ticks += 1
            due = self._ticks % self.interval == 0
        if not due:
            return None
        stats = scenario_stats_fn() if scenario_stats_fn is not None \
            else None
        return self.update(stats)

    def assign(self, num_fleets):
        """Apportion the current mix over ``num_fleets`` fleets
        (largest remainder, catalog order — deterministic)."""
        return apportion(self.mix(), num_fleets)

    def stats(self):
        with self._lock:
            return {
                "policy": self.policy,
                "interval": self.interval,
                "mix": dict(self._mix),
                "returns_ema": dict(self._returns),
                "updates": self._updates,
                "mix_changes": self._changes,
            }

    # -- checkpoint surface (learner failover; docs/fault_tolerance.md) ------

    def state_dict(self):
        """JSON-able snapshot of everything :meth:`tick` evolves —
        policy, current/pinned mix, per-scenario return EMAs, and the
        tick/update/change counters — so a restored learner's
        curriculum continues from the cut instead of restarting at the
        uniform mix (the interval gate included: a curriculum shift
        due 3 updates after the cut stays due 3 updates after the
        resume)."""
        with self._lock:
            return {
                "names": list(self.names),
                "policy": self.policy,
                "mix": dict(self._mix),
                "pinned": dict(self._pinned) if self._pinned else None,
                "returns_ema": dict(self._returns),
                "ticks": self._ticks,
                "updates": self._updates,
                "changes": self._changes,
            }

    def load_state_dict(self, state):
        """Restore a :meth:`state_dict` snapshot.  The scenario name
        set must match — a checkpoint from a different catalog would
        silently misweight fleets."""
        names = list(state.get("names", []))
        if names != self.names:
            raise ValueError(
                f"curriculum checkpoint spans scenarios {names}, this "
                f"scheduler has {self.names}; restore with the same "
                "catalog"
            )
        if state["policy"] not in POLICIES:
            raise ValueError(
                f"unknown curriculum policy {state['policy']!r} in "
                f"checkpoint; one of {POLICIES}"
            )
        with self._lock:
            self.policy = state["policy"]
            self._mix = {n: float(state["mix"][n]) for n in self.names}
            pinned = state.get("pinned")
            self._pinned = (
                {n: float(pinned[n]) for n in self.names}
                if pinned else None
            )
            self._returns = {
                n: float(v) for n, v in
                (state.get("returns_ema") or {}).items()
                if n in self.names
            }
            self._ticks = int(state.get("ticks", 0))
            self._updates = int(state.get("updates", 0))
            self._changes = int(state.get("changes", 0))
