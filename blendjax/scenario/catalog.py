"""Scenario catalog: named, validated, seeded-samplable scene configs.

The fleet can heal, shard, and hot-swap weights, but every env renders
the same scene (ROADMAP #5).  A :class:`ScenarioSpec` names one scene
configuration — fixed scene params, per-param randomization ranges, a
physics rate, a render resolution — and a :class:`ScenarioCatalog`
holds the named set the rest of the scenario plane works in terms of:

- the :class:`~blendjax.scenario.randomize.DomainRandomizer` samples a
  spec (``spec.sample(rng)`` -> concrete param dict) and pushes the
  draw into running producers over the duplex control plane;
- the :class:`~blendjax.scenario.curriculum.CurriculumScheduler`
  reweights the fleet's mix over the catalog's names;
- replay strata, telemetry records, and serve-tier traffic labels all
  key on the catalog's scenario NAMES (strings on the wire, interned
  to small ints inside the replay ring).

Specs round-trip through JSON (:meth:`ScenarioCatalog.to_json` /
:meth:`from_json`) with schema validation on the way in: unknown
fields, inverted ranges, non-numeric bounds, and duplicate names are
errors at load time, not mid-training.  See docs/scenarios.md.
"""

from __future__ import annotations

import json

#: format tag carried by every serialized catalog (rejecting a foreign
#: JSON document with a useful error instead of a KeyError mid-field)
CATALOG_FORMAT = "blendjax.scenario/1"

#: the spec fields a serialized document may carry — anything else is a
#: schema error (a typo'd ``rangs`` must not silently become a no-op)
_SPEC_FIELDS = ("params", "ranges", "physics_rate_us", "resolution")


def _validate_ranges(name, ranges):
    out = {}
    for key, rng in dict(ranges or {}).items():
        if isinstance(rng, (list, tuple)) and len(rng) == 2 and all(
            isinstance(v, (int, float)) and not isinstance(v, bool)
            for v in rng
        ):
            lo, hi = float(rng[0]), float(rng[1])
            if lo > hi:
                raise ValueError(
                    f"scenario {name!r}: range {key!r} inverted "
                    f"({lo} > {hi})"
                )
            out[key] = (lo, hi)
        elif isinstance(rng, (list, tuple)) and len(rng) > 0 and all(
            isinstance(v, (str, int, float, bool)) for v in rng
        ):
            # any other scalar sequence is a CHOICE list (a 2-tuple of
            # numbers is always an interval — use a 2-element choice of
            # strings/bools, or repeat an element, to force choices)
            out[key] = list(rng)
        else:
            raise ValueError(
                f"scenario {name!r}: range {key!r} must be a numeric "
                f"(lo, hi) pair or a choice list, got {rng!r}"
            )
    return out


class ScenarioSpec:
    """One named scene configuration.

    Params
    ------
    name: str
        Catalog key; also the label stamped on transitions, replay
        rows, telemetry records and serve traffic.
    params: dict | None
        Fixed scene parameters pushed verbatim with every sample
        (e.g. ``{"scene": "warehouse", "clutter": 3}``).
    ranges: dict | None
        Per-parameter randomization: a numeric ``(lo, hi)`` pair draws
        uniformly; a choice list draws one element.  Drawn fresh per
        :meth:`sample`, overlaid on ``params``.
    physics_rate_us: int
        The scenario's per-frame physics cost (the producer's solver
        tick stand-in) — what makes fleets HETEROGENEOUS; rides every
        sample as ``physics_us``.
    resolution: (int, int) | None
        Render resolution ``(h, w)``; rides every sample when set.
    """

    __slots__ = ("name", "params", "ranges", "physics_rate_us",
                 "resolution")

    def __init__(self, name, *, params=None, ranges=None,
                 physics_rate_us=0, resolution=None):
        if not isinstance(name, str) or not name:
            raise ValueError(f"scenario name must be a non-empty "
                             f"string, got {name!r}")
        self.name = name
        self.params = dict(params or {})
        self.ranges = _validate_ranges(name, ranges)
        self.physics_rate_us = int(physics_rate_us)
        if self.physics_rate_us < 0:
            raise ValueError(
                f"scenario {name!r}: physics_rate_us must be >= 0"
            )
        if resolution is not None:
            resolution = tuple(int(v) for v in resolution)
            if len(resolution) != 2 or min(resolution) < 1:
                raise ValueError(
                    f"scenario {name!r}: resolution must be a positive "
                    f"(h, w) pair, got {resolution!r}"
                )
        self.resolution = resolution

    def sample(self, rng):
        """One concrete parameter dict from a seeded
        ``numpy.random.Generator``: fixed ``params``, a fresh uniform /
        choice draw per range, plus the spec's ``physics_us`` /
        ``resolution`` and the ``scenario`` name itself — the dict a
        randomization push carries in full."""
        out = dict(self.params)
        # deterministic draw order: sorted keys, one rng call per key
        for key in sorted(self.ranges):
            rng_spec = self.ranges[key]
            if isinstance(rng_spec, tuple):
                lo, hi = rng_spec
                out[key] = float(lo + (hi - lo) * rng.random())
            else:
                out[key] = rng_spec[int(rng.integers(len(rng_spec)))]
        out["scenario"] = self.name
        # ALWAYS emitted, zero included: a producer reassigned from a
        # slow scenario to a free one must have its rate reset, not
        # keep the old physics while relabelling
        out["physics_us"] = self.physics_rate_us
        if self.resolution is not None:
            out["resolution"] = list(self.resolution)
        return out

    def env_kwargs(self):
        """The LAUNCH-time kwargs for a fleet pinned to this scenario
        (``FleetSet(fleet_env_kwargs=...)``): the knobs the test env
        fixture understands at spawn, before any duplex push lands."""
        return {"scenario": self.name,
                "physics_us": self.physics_rate_us}

    def to_dict(self):
        d = {"params": dict(self.params),
             "ranges": {k: list(v) if isinstance(v, tuple) else list(v)
                        for k, v in self.ranges.items()},
             "physics_rate_us": self.physics_rate_us}
        if self.resolution is not None:
            d["resolution"] = list(self.resolution)
        return d

    @classmethod
    def from_dict(cls, name, d):
        if not isinstance(d, dict):
            raise ValueError(
                f"scenario {name!r}: spec must be an object, got "
                f"{type(d).__name__}"
            )
        unknown = sorted(set(d) - set(_SPEC_FIELDS))
        if unknown:
            raise ValueError(
                f"scenario {name!r}: unknown spec field(s) {unknown}; "
                f"known: {list(_SPEC_FIELDS)}"
            )
        return cls(
            name,
            params=d.get("params"),
            ranges=d.get("ranges"),
            physics_rate_us=d.get("physics_rate_us", 0),
            resolution=d.get("resolution"),
        )

    def __repr__(self):
        return (f"ScenarioSpec({self.name!r}, "
                f"physics_rate_us={self.physics_rate_us}, "
                f"ranges={sorted(self.ranges)})")


class ScenarioCatalog:
    """Ordered named set of :class:`ScenarioSpec`.

    Insertion order is the canonical scenario order (apportionment and
    strata reports iterate it), so a catalog built the same way always
    assigns the same fleets the same scenarios.
    """

    def __init__(self, specs=()):
        self._specs = {}
        for spec in specs:
            self.add(spec)

    def add(self, spec):
        if not isinstance(spec, ScenarioSpec):
            raise TypeError(f"expected ScenarioSpec, got {spec!r}")
        if spec.name in self._specs:
            raise ValueError(f"duplicate scenario name {spec.name!r}")
        self._specs[spec.name] = spec
        return self

    def get(self, name):
        try:
            return self._specs[name]
        except KeyError:
            raise KeyError(
                f"unknown scenario {name!r}; catalog has "
                f"{self.names()}"
            ) from None

    def names(self):
        return list(self._specs)

    def sample(self, name, rng):
        return self.get(name).sample(rng)

    def __len__(self):
        return len(self._specs)

    def __iter__(self):
        return iter(self._specs.values())

    def __contains__(self, name):
        return name in self._specs

    # -- JSON round trip -----------------------------------------------------

    def to_json(self, indent=None):
        return json.dumps(
            {"format": CATALOG_FORMAT,
             "scenarios": {s.name: s.to_dict() for s in self}},
            indent=indent,
        )

    @classmethod
    def from_json(cls, text):
        doc = json.loads(text)
        if not isinstance(doc, dict) \
                or doc.get("format") != CATALOG_FORMAT:
            raise ValueError(
                f"not a scenario catalog (format "
                f"{doc.get('format') if isinstance(doc, dict) else None!r}"
                f"; expected {CATALOG_FORMAT!r})"
            )
        cat = cls()
        for name, d in doc.get("scenarios", {}).items():
            cat.add(ScenarioSpec.from_dict(name, d))
        return cat

    def save(self, path):
        with open(path, "w") as f:
            f.write(self.to_json(indent=2))
        return path

    @classmethod
    def load(cls, path):
        with open(path) as f:
            return cls.from_json(f.read())

    def __repr__(self):
        return f"ScenarioCatalog({self.names()})"
