"""Live domain randomization over the duplex control plane.

The reference's densityopt workflow pushes scene parameters into a
RUNNING Blender instance over the PAIR duplex channel mid-training
(``examples/densityopt/densityopt.py:95-107``).  The
:class:`DomainRandomizer` generalizes that into the fleet's scenario
control plane:

- each producer binds a ``CTRL`` PAIR socket next to its ``GYM`` one
  (``FleetSet(ctrl=True)`` allocates the addresses; the env script
  attaches it via :meth:`blendjax.btb.env.BaseEnv.attach_param_channel`
  and applies pushes through its ``_env_apply_params`` hook);
- the randomizer holds one consumer-side
  :class:`~blendjax.btt.duplex.DuplexChannel` per producer, samples a
  :class:`~blendjax.scenario.catalog.ScenarioSpec` (seeded), and sends
  the concrete param dict — per assignment change, per episode, or per
  K steps, as the caller paces it;
- pushes are **bounded, never blocking**: a SIGKILLed producer makes
  ``send`` time out (counted ``scenario_push_failures``), it cannot
  wedge the pushing thread — the chaos contract;
- producers echo the applied scenario name in every transition
  (``info["scenario"]``), which is how a push is CONFIRMED
  (:meth:`note_info` -> ``scenario_applies``) and how replay rows and
  telemetry attribute to scenarios even across reassignment races;
- a re-admitted env (``info["readmitted"]``) gets its fleet's current
  scenario re-pushed over a FRESH channel
  (:meth:`reassign` -> ``scenario_reassignments``) so a respawned
  producer never keeps serving a stale scene.

See docs/scenarios.md for the push protocol and counter tables.
"""

from __future__ import annotations

import logging
import threading
import time

import numpy as np

from blendjax.utils.timing import StageTimer, fleet_counters

logger = logging.getLogger("blendjax")

#: wire command tag of a randomization push (the producer-side hook
#: dispatches on it; unknown commands are ignored by the default hook)
PUSH_CMD = "scenario"

#: default bound on one duplex push send, milliseconds.  PAIR queues up
#: to HWM (10) frames to a dead peer before Again — small enough that a
#: fleet-wide reassignment over one dead producer costs tens of ms, not
#: the socket default's tens of seconds.
DEFAULT_PUSH_TIMEOUT_MS = 200


def _as_fleet_lists(addresses):
    """Normalize ``ctrl_addresses``: a flat list of endpoints is ONE
    fleet; a list of lists is one entry per fleet."""
    addresses = list(addresses)
    if addresses and isinstance(addresses[0], str):
        return [addresses]
    return [list(f) for f in addresses]


class DomainRandomizer:
    """Samples scenarios and pushes the draws into running producers.

    Params
    ------
    catalog: ScenarioCatalog
        The named scenario set assignments come from.
    ctrl_addresses: list[str] | list[list[str]]
        Producer CTRL endpoints — flat (one fleet) or per-fleet
        (``FleetSet.ctrl_addresses``).
    seed: int
        Seeds the sampling RNG: same catalog + same push sequence ->
        identical parameter draws.
    push_timeout_ms: int
        Bound on each duplex send (see module docstring).
    resample_every: int | None
        When set, :meth:`maybe_resample` re-pushes a fresh draw of the
        CURRENT scenario to a fleet every N calls (per-K-steps
        randomization); None leaves pacing entirely to the caller.
    counters / timer:
        ``SCENARIO_EVENTS`` sink and ``SCENARIO_STAGES`` timer;
        defaults to the process-wide ``fleet_counters`` / a private
        timer.
    """

    def __init__(self, catalog, ctrl_addresses, *, seed=0,
                 push_timeout_ms=DEFAULT_PUSH_TIMEOUT_MS,
                 resample_every=None, counters=None, timer=None):
        self.catalog = catalog
        self._fleets = _as_fleet_lists(ctrl_addresses)
        self.num_fleets = len(self._fleets)
        self.counters = counters if counters is not None else fleet_counters
        self.timer = timer if timer is not None else StageTimer()
        self.push_timeout_ms = int(push_timeout_ms)
        self.resample_every = (
            None if resample_every is None else max(1, int(resample_every))
        )
        self._rng = np.random.default_rng(seed)
        # two-lock discipline: ``_lock`` guards the assignment /
        # confirmation state and the channel dict with SHORT holds, so
        # the actor hot path (``note_info``/``scenario_of``, once per
        # transition) never waits behind a network send; ``_push_lock``
        # serializes the push pipeline itself — RNG draws (numpy
        # Generators are not thread-safe; the documented deterministic
        # draw sequence needs one serialized consumer) and the zmq
        # PAIR sends (one socket per env, not thread-safe) — across
        # the learner's reassignments, an actor's respawn re-push and
        # per-K resampling.  Order: ``_push_lock`` outer, ``_lock``
        # inner, never the reverse.
        self._lock = threading.RLock()
        self._push_lock = threading.RLock()
        self._chans = {}          # (fleet, env) -> DuplexChannel
        self._assigned = [None] * self.num_fleets
        self._confirmed = [False] * self.num_fleets
        self._step_ticks = [0] * self.num_fleets
        self._closed = False

    # -- channels ------------------------------------------------------------

    def _channel(self, f, i, fresh=False):
        """The consumer-side PAIR channel to producer ``(f, i)``
        (lazy-dialed; ``fresh=True`` re-dials — the respawn path, where
        frames queued to the dead incarnation must not replay into the
        new one)."""
        from blendjax.btt.duplex import DuplexChannel

        key = (f, i)
        with self._lock:
            chan = self._chans.get(key)
            if fresh and chan is not None:
                try:
                    chan.close()
                except Exception:  # noqa: BLE001 - teardown best-effort
                    pass
                chan = None
            if chan is None:
                chan = DuplexChannel(
                    self._fleets[f][i], btid=i,
                    timeoutms=self.push_timeout_ms,
                )
                self._chans[key] = chan
            return chan

    # -- assignment & pushes -------------------------------------------------

    @property
    def assignments(self):
        """Current scenario name per fleet (None = never assigned)."""
        with self._lock:
            return list(self._assigned)

    def scenario_of(self, fleet_id):
        with self._lock:
            return self._assigned[fleet_id]

    def _sample_spec(self, spec):
        """One seeded draw (``_push_lock`` held: the RNG has exactly
        one serialized consumer, keeping the documented deterministic
        draw sequence)."""
        t0 = time.perf_counter()
        params = spec.sample(self._rng)
        self.counters.incr("scenario_samples")
        self.timer.add("scenario_sample", time.perf_counter() - t0,
                       _t0=t0)
        return params

    def assign(self, fleet_id, scenario, *, fresh_channel=False,
               count_reassignment=False):
        """Assign ``scenario`` to every env of ``fleet_id`` and push a
        fresh sampled param dict to each.  Returns the number of envs
        the push reached (a dead producer is counted and skipped, never
        blocked on).  The sends run outside the state lock: an actor
        thread reading ``scenario_of``/``note_info`` never waits
        behind a reassignment's network round."""
        spec = self.catalog.get(scenario)  # raises on unknown names
        with self._push_lock:
            with self._lock:
                if self._closed:
                    return 0
                self._assigned[fleet_id] = spec.name
                self._confirmed[fleet_id] = False
                n_envs = len(self._fleets[fleet_id])
            delivered = 0
            for i in range(n_envs):
                params = self._sample_spec(spec)
                if self._push(fleet_id, i, params,
                              fresh_channel=fresh_channel):
                    delivered += 1
                if count_reassignment:
                    self.counters.incr("scenario_reassignments")
            return delivered

    def _push(self, f, i, params, fresh_channel=False):
        """One bounded duplex send; True when the frame was queued to a
        live peer.  zmq.Again (dead/stalled producer past the HWM) is a
        counted failure — the caller's thread NEVER wedges here."""
        import zmq

        t0 = time.perf_counter()
        try:
            chan = self._channel(f, i, fresh=fresh_channel)
            chan.send(cmd=PUSH_CMD, scenario=params.get("scenario"),
                      params=params)
        except zmq.Again:
            self.counters.incr("scenario_push_failures")
            self.timer.add("scenario_push",
                           time.perf_counter() - t0, _t0=t0)
            logger.warning(
                "scenario push to fleet %d env %d timed out "
                "(producer dead or stalled); continuing", f, i,
            )
            return False
        except zmq.ZMQError as exc:
            self.counters.incr("scenario_push_failures")
            self.timer.add("scenario_push",
                           time.perf_counter() - t0, _t0=t0)
            logger.warning(
                "scenario push to fleet %d env %d failed (%s)", f, i, exc,
            )
            return False
        self.counters.incr("scenario_pushes")
        self.timer.add("scenario_push", time.perf_counter() - t0, _t0=t0)
        return True

    def apply_assignment(self, assignment):
        """Drive a full per-fleet assignment (the curriculum's output):
        only fleets whose scenario CHANGED are pushed.  Returns the list
        of fleet ids that changed."""
        if len(assignment) != self.num_fleets:
            raise ValueError(
                f"assignment names {len(assignment)} fleets, randomizer "
                f"has {self.num_fleets}"
            )
        changed = []
        for f, name in enumerate(assignment):
            if name is None or name == self.scenario_of(f):
                continue
            self.assign(f, name)
            changed.append(f)
        return changed

    def reassign(self, fleet_id, env_index):
        """Re-push the fleet's current scenario to ONE env over a fresh
        channel — the respawn/re-admission path (the new producer
        incarnation starts with the default scene; its scenario must
        follow it).  No-op for a never-assigned fleet."""
        with self._lock:
            name = self._assigned[fleet_id]
        if name is None:
            return False
        spec = self.catalog.get(name)
        with self._push_lock:
            params = self._sample_spec(spec)
            ok = self._push(fleet_id, env_index, params,
                            fresh_channel=True)
        self.counters.incr("scenario_reassignments")
        with self._lock:
            self._confirmed[fleet_id] = False
        return ok

    def maybe_resample(self, fleet_id):
        """Per-K-steps randomization: called once per fleet step, pushes
        a fresh draw of the CURRENT scenario every ``resample_every``
        calls.  Inert when ``resample_every`` is None."""
        if self.resample_every is None:
            return False
        with self._lock:
            self._step_ticks[fleet_id] += 1
            due = self._step_ticks[fleet_id] % self.resample_every == 0
            name = self._assigned[fleet_id]
        if not due or name is None:
            return False
        spec = self.catalog.get(name)
        with self._push_lock:
            for i in range(len(self._fleets[fleet_id])):
                self._push(fleet_id, i, self._sample_spec(spec))
        return True

    def note_info(self, fleet_id, info):
        """Confirmation hook: the first data-plane transition stamped
        with the fleet's newly-pushed scenario closes the push loop
        (``scenario_applies``).  Cheap enough to call per transition."""
        sid = info.get("scenario")
        if sid is None:
            return
        with self._lock:
            if not self._confirmed[fleet_id] \
                    and sid == self._assigned[fleet_id]:
                self._confirmed[fleet_id] = True
                self.counters.incr("scenario_applies")

    def stats(self):
        """One scenario-plane snapshot: assignments, confirmations,
        and the push/sample stage timings."""
        with self._lock:
            return {
                "num_fleets": self.num_fleets,
                "assignments": list(self._assigned),
                "confirmed": list(self._confirmed),
                "scenarios": self.catalog.names(),
                "stages": self.timer.summary(),
            }

    def close(self):
        # the push lock first: an in-flight push finishes (bounded by
        # its timeout) before its channel is closed under it
        with self._push_lock:
            with self._lock:
                self._closed = True
                chans, self._chans = self._chans, {}
            for chan in chans.values():
                try:
                    chan.close()
                except Exception:  # noqa: BLE001 - teardown best-effort
                    pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
