"""AutoscaleController: metric-driven serve-fleet resize with rollback.

The capacity half of the closed-loop discipline
(docs/autoscaling.md): the gateway already *scrapes* every replica's
queue depth, live-episode count and p99 — this controller turns those
scrapes into ``grow`` / ``drain`` / ``retire`` decisions, with the
same verify-then-commit shape as the
:class:`~blendjax.weights.controller.WeightBusController`:

- **scale up** when load (mean queue depth OR fleet p99) crosses the
  upper hysteresis band: spawn one replica
  (:meth:`~blendjax.serve.server.ServerFleet.grow`), admit it to the
  gateway, then hold a **healthy window** — a fleet error-rate or
  latency regression inside the window ROLLS the newcomer back out
  (drain + retire, ``autoscale_rollbacks``) instead of committing it;
- **scale down** when load sits below the lower band: **drain** the
  least-loaded replica (fresh episodes stop, live leases finish or
  idle out under ``drain_grace_s``), verify the shrunk route set
  through the same healthy window, and only then retire the process —
  a drain that cannot empty in time, or a window regression, re-admits
  the replica untouched;
- **hysteresis + cooldowns**: the bands between the up and down
  thresholds, plus per-direction cooldowns and ``min_replicas``/
  ``max_replicas`` bounds, keep the loop from flapping
  (``autoscale_holds`` counts suppressed firings);
- **crash-safe by statelessness**: every decision is re-derived from
  the observed fleet (gateway snapshots + counters), never from
  controller memory a crash could lose.  A restarted controller that
  finds a replica already draining ADOPTS that transition
  (``autoscale_adoptions``) and carries it to its verdict — it never
  issues a second, conflicting action.

One transition is in flight at a time; :meth:`tick` advances it one
step per call (what makes every phase individually testable and a
mid-transition controller death recoverable).  Drive :meth:`tick` from
your own loop or :meth:`start` a daemon thread.

Replica ids follow the fleet-index convention ``r<idx>`` (what
:class:`~blendjax.serve.gateway.ServeGateway` allocates for the
initial fleet and what this controller passes explicitly on
admission), so a gateway id maps back to the
:class:`~blendjax.serve.server.ServerFleet` slot without a side table
a crash could lose.
"""

from __future__ import annotations

import logging
import threading
import time

from blendjax.utils.timing import StageTimer, fleet_counters

logger = logging.getLogger("blendjax")


class AutoscaleController:
    """Closed-loop serve-fleet resizing over one
    :class:`~blendjax.serve.gateway.ServeGateway` and the
    :class:`~blendjax.serve.server.ServerFleet` whose processes it
    routes to.

    Params
    ------
    gateway: ServeGateway
        The in-process gateway whose scrape state drives decisions and
        whose ``add_replica``/``drain``/``remove_replica`` this
        controller calls.
    fleet: ServerFleet
        The replica processes; ``grow``/``retire`` side of a resize.
    min_replicas / max_replicas: int
        Hard bounds on ACTIVE (non-draining) replicas.
    up_queue_depth / up_p99_ms: float
        Upper hysteresis band: mean queued-per-replica OR fleet p99
        above either triggers a scale-up.
    down_queue_depth / down_p99_ms: float
        Lower band: BOTH below triggers a scale-down.  Load between
        the bands is the stable region — no action, no hold counted.
    cooldown_up_s / cooldown_down_s: float
        Minimum spacing between committed transitions per direction
        (rollbacks also arm the cooldown — a resize that just failed
        should not retry next tick).
    healthy_window_s: float
        Post-action verification window before a transition commits.
    min_requests: int
        Fleet replies observed inside the window before an error-rate
        verdict (one slow request must not roll a resize back).
    max_error_rate: float
        Fleet error fraction inside the window above which the
        transition rolls back.
    max_p99_x: float
        Newcomer p99 over the incumbent median above which a scale-up
        rolls back (skipped while incumbents have no latency history).
    drain_grace_s: float
        Bound on a scale-down drain: leases still live past it
        re-admit the replica (``autoscale_drain_timeouts``).
    """

    def __init__(self, gateway, fleet, *, min_replicas=1, max_replicas=8,
                 up_queue_depth=8.0, up_p99_ms=200.0,
                 down_queue_depth=1.0, down_p99_ms=50.0,
                 cooldown_up_s=5.0, cooldown_down_s=10.0,
                 healthy_window_s=3.0, min_requests=20,
                 max_error_rate=0.02, max_p99_x=2.0,
                 drain_grace_s=10.0, counters=None, timer=None):
        self.gateway = gateway
        self.fleet = fleet
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self.up_queue_depth = float(up_queue_depth)
        self.up_p99_ms = float(up_p99_ms)
        self.down_queue_depth = float(down_queue_depth)
        self.down_p99_ms = float(down_p99_ms)
        self.cooldown_up_s = float(cooldown_up_s)
        self.cooldown_down_s = float(cooldown_down_s)
        self.healthy_window_s = float(healthy_window_s)
        self.min_requests = int(min_requests)
        self.max_error_rate = float(max_error_rate)
        self.max_p99_x = float(max_p99_x)
        self.drain_grace_s = float(drain_grace_s)
        self.counters = counters if counters is not None else fleet_counters
        self.timer = timer if timer is not None else StageTimer()
        #: the ONE in-flight transition (None = idle): kind "up"/"down",
        #: rid, stage "drain"/"verify", t0, deadlines, counter baseline.
        #: Deliberately reconstructible: a fresh controller re-derives
        #: an equivalent record from gateway state (see _adopt).
        self._transition = None
        self._cooldown_until = {"up": 0.0, "down": 0.0}
        self._thread = None
        self._stop = None

    # -- scraped state views -------------------------------------------------

    def _active(self, snaps):
        """Healthy, non-draining replica snapshots (the route set a
        decision sizes against)."""
        return {
            rid: rec for rid, rec in snaps.items()
            if rec["healthy"] and not rec["draining"]
        }

    def _load(self, active):
        """(mean queued per replica, max p99_ms) over the active set."""
        if not active:
            return 0.0, 0.0
        queued = sum(r["queued"] for r in active.values()) / len(active)
        p99 = max(r["p99_ms"] for r in active.values())
        return float(queued), float(p99)

    def _req_err(self):
        g = self.gateway.counters
        return g.get("gateway_requests"), g.get("gateway_errors")

    def _window_regression(self, base):
        """Fleet-wide error-rate verdict over the window so far; None
        while healthy (or too little traffic to judge)."""
        req0, err0 = base
        req, err = self._req_err()
        d_req, d_err = req - req0, err - err0
        if d_req >= self.min_requests \
                and (d_err / d_req) > self.max_error_rate:
            return (f"error rate {d_err / d_req:.3f} > "
                    f"{self.max_error_rate} over {d_req} requests")
        return None

    @staticmethod
    def _fleet_idx(rid):
        """Gateway id -> fleet slot under the ``r<idx>`` convention
        (None for ids outside it — nothing to retire then)."""
        if rid.startswith("r") and rid[1:].isdigit():
            return int(rid[1:])
        return None

    # -- the decision tick ---------------------------------------------------

    def tick(self):
        """One control step; returns the action taken (``"grow" |
        "drain" | "scale_up" | "scale_down" | "rollback" | "adopt" |
        "hold" | None``).  Advances an in-flight transition by one
        stage, else evaluates the scaling rules."""
        t0 = time.perf_counter()
        self.counters.incr("autoscale_ticks")
        try:
            if self._transition is None:
                adopted = self._adopt()
                if adopted is not None:
                    return adopted
            if self._transition is not None:
                return self._advance()
            return self._decide()
        finally:
            self.timer.add("autoscale_tick",
                           time.perf_counter() - t0, _t0=t0)

    def _adopt(self):
        """Idempotence against a controller death mid-transition: a
        replica observed already draining becomes OUR scale-down at its
        drain stage — the decision is finished, never re-issued."""
        snaps = self.gateway.replica_snapshots()
        for rid, rec in snaps.items():
            if rec["draining"] and rec["healthy"]:
                now = time.monotonic()
                self._transition = {
                    "kind": "down", "rid": rid, "stage": "drain",
                    "t0": now, "deadline": now + self.drain_grace_s,
                    "base": self._req_err(),
                }
                self.counters.incr("autoscale_adoptions")
                logger.warning(
                    "autoscale: adopted in-flight drain of %s (a prior "
                    "controller's decision); carrying it to a verdict",
                    rid,
                )
                return "adopt"
        return None

    def _decide(self):
        snaps = self.gateway.replica_snapshots()
        active = self._active(snaps)
        queued, p99 = self._load(active)
        n = len(active)
        now = time.monotonic()
        wants_up = queued > self.up_queue_depth or p99 > self.up_p99_ms
        wants_down = (queued < self.down_queue_depth
                      and p99 < self.down_p99_ms)
        if wants_up:
            if n >= self.max_replicas or now < self._cooldown_until["up"]:
                self.counters.incr("autoscale_holds")
                return "hold"
            return self._begin_up(n, queued, p99)
        if wants_down:
            if n <= self.min_replicas \
                    or now < self._cooldown_until["down"]:
                self.counters.incr("autoscale_holds")
                return "hold"
            return self._begin_down(active, queued, p99)
        return None  # inside the hysteresis band: stable

    def _begin_up(self, n, queued, p99):
        t0 = time.monotonic()
        base = self._req_err()
        [(idx, address)] = self.fleet.grow(1)
        self.counters.incr("autoscale_replica_spawns")
        rid = self.gateway.add_replica(address, rid=f"r{idx}")
        self._transition = {
            "kind": "up", "rid": rid, "idx": idx, "stage": "verify",
            "t0": t0, "deadline": t0 + self.healthy_window_s,
            "base": base,
        }
        logger.warning(
            "autoscale: scaling UP %d -> %d (queued %.1f, p99 %.0fms); "
            "replica %s spawned at %s, verifying for %.1fs",
            n, n + 1, queued, p99, rid, address, self.healthy_window_s,
        )
        return "grow"

    def _begin_down(self, active, queued, p99):
        # victim: the least-loaded active replica — fewest live leases
        # to wait out, least traffic disturbed
        rid = min(active, key=lambda r: (
            active[r]["live_episodes"] + 4 * active[r]["queued"]
            + active[r]["p99_ms"] / 100.0
        ))
        t0 = time.monotonic()
        base = self._req_err()
        self.gateway.drain(rid)
        self._transition = {
            "kind": "down", "rid": rid, "stage": "drain",
            "t0": t0, "deadline": t0 + self.drain_grace_s,
            "base": base,
        }
        logger.warning(
            "autoscale: scaling DOWN %d -> %d (queued %.1f, p99 "
            "%.0fms); draining %s (grace %.1fs)",
            len(active), len(active) - 1, queued, p99, rid,
            self.drain_grace_s,
        )
        return "drain"

    # -- advancing the in-flight transition ----------------------------------

    def _advance(self):
        tr = self._transition
        if tr["kind"] == "up":
            return self._advance_up(tr)
        return self._advance_down(tr)

    def _advance_up(self, tr):
        rid = tr["rid"]
        now = time.monotonic()
        snaps = self.gateway.replica_snapshots()
        rec = snaps.get(rid)
        regression = self._window_regression(tr["base"])
        if regression is None and rec is not None and rec["healthy"] \
                and rec["p99_ms"] > 0:
            others = [r["p99_ms"] for i, r in snaps.items()
                      if i != rid and r["healthy"] and r["p99_ms"] > 0]
            if others:
                others.sort()
                med = others[len(others) // 2]
                if rec["p99_ms"] > self.max_p99_x * med:
                    regression = (
                        f"newcomer p99 {rec['p99_ms']:.0f}ms > "
                        f"{self.max_p99_x}x incumbent {med:.0f}ms"
                    )
        if regression is not None:
            return self._rollback_up(tr, regression)
        if now < tr["deadline"]:
            return None  # window still open, healthy so far
        if rec is None or not rec["healthy"]:
            return self._rollback_up(
                tr, "newcomer never turned healthy in the window"
            )
        self._transition = None
        self._cooldown_until["up"] = now + self.cooldown_up_s
        dt = now - tr["t0"]
        self.timer.add("autoscale_resize", dt, _t0=tr["t0"])
        self.counters.incr("autoscale_scale_ups")
        logger.warning(
            "autoscale: scale-up committed — %s healthy through the "
            "window (%.2fs decision-to-settle)", rid, dt,
        )
        return "scale_up"

    def _rollback_up(self, tr, why):
        rid, idx = tr["rid"], tr["idx"]
        # the newcomer never owned committed traffic: drain (stops
        # fresh routes; any lease it did pick up dies with the removal
        # and the owning client fails over via the stale-lease error)
        # and retire on the spot
        try:
            self.gateway.drain(rid)
        except KeyError:
            pass  # never admitted — nothing routed to it
        self.gateway.remove_replica(rid)
        self.fleet.retire(idx)
        self._transition = None
        self._cooldown_until["up"] = (
            time.monotonic() + self.cooldown_up_s
        )
        self.counters.incr("autoscale_rollbacks")
        logger.error(
            "autoscale: scale-up of %s ROLLED BACK (%s); fleet back at "
            "its prior size", rid, why,
        )
        return "rollback"

    def _advance_down(self, tr):
        rid = tr["rid"]
        now = time.monotonic()
        if tr["stage"] == "drain":
            if self.gateway.lease_count(rid) == 0:
                dt = now - tr["t0"]
                self.timer.add("autoscale_drain", dt, _t0=tr["t0"])
                tr["stage"] = "verify"
                tr["deadline"] = now + self.healthy_window_s
                logger.info(
                    "autoscale: %s drained (%.2fs); verifying the "
                    "shrunk route set for %.1fs", rid, dt,
                    self.healthy_window_s,
                )
                return None
            if now >= tr["deadline"]:
                self.gateway.undrain(rid)
                self._transition = None
                self._cooldown_until["down"] = (
                    now + self.cooldown_down_s
                )
                self.counters.incr("autoscale_drain_timeouts")
                self.counters.incr("autoscale_rollbacks")
                logger.error(
                    "autoscale: drain of %s timed out with %d live "
                    "leases after %.1fs; re-admitted (rollback)",
                    rid, self.gateway.lease_count(rid),
                    self.drain_grace_s,
                )
                return "rollback"
            return None  # leases still finishing
        # verify stage: the fleet minus the drained replica must stay
        # healthy before the process is actually retired
        regression = self._window_regression(tr["base"])
        if regression is not None:
            self.gateway.undrain(rid)
            self._transition = None
            self._cooldown_until["down"] = now + self.cooldown_down_s
            self.counters.incr("autoscale_rollbacks")
            logger.error(
                "autoscale: scale-down of %s ROLLED BACK (%s); replica "
                "re-admitted untouched", rid, regression,
            )
            return "rollback"
        if now < tr["deadline"]:
            return None
        self.gateway.remove_replica(rid)
        idx = self._fleet_idx(rid)
        if idx is not None:
            self.fleet.retire(idx)
        self._transition = None
        self._cooldown_until["down"] = now + self.cooldown_down_s
        dt = now - tr["t0"]
        self.timer.add("autoscale_resize", dt, _t0=tr["t0"])
        self.counters.incr("autoscale_replicas_retired")
        self.counters.incr("autoscale_scale_downs")
        logger.warning(
            "autoscale: scale-down committed — %s retired (%.2fs "
            "decision-to-settle)", rid, dt,
        )
        return "scale_down"

    # -- background driving --------------------------------------------------

    def start(self, interval_s=0.25):
        if self._thread is not None:
            return self
        self._stop = threading.Event()

        def loop():
            while not self._stop.is_set():
                try:
                    self.tick()
                except Exception:  # noqa: BLE001 - controller survives
                    logger.exception("autoscale controller tick failed")
                self._stop.wait(interval_s)

        self._thread = threading.Thread(
            target=loop, daemon=True, name="bjx-autoscale-controller"
        )
        self._thread.start()
        return self

    def stop(self):
        if self._thread is not None:
            self._stop.set()
            self._thread.join(timeout=5)
            self._thread = None
            self._stop = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()
        return False
