"""Live replay resharding: grow the shard fleet under traffic.

The replay tier's resize is not a routing change — the client IS the
draw authority, so adding storage means moving slot *ownership*
crash-exactly (docs/autoscaling.md "Shard handoff"):

1. the source shard checkpoints its full state (``save`` RPC — the
   PR-15 durability machinery; appends keep flowing after the cut);
2. :meth:`~blendjax.replay.service.ShardFleet.grow` copies that
   checkpoint under the new shard's name and spawns it — the newcomer
   boots already holding every source row up to the cut;
3. :meth:`~blendjax.replay.shard_client.ShardedReplay.adopt_shard`
   verifies the restore, copies only the rows appended past the cut
   into the moving range (``written_since`` reconciliation), and flips
   ownership of the range under the buffer lock.

Total capacity, the SumTree and the RNG never change, so the draw
stream continues bit-identically over unmoved ranges — the same
argument that makes an N-shard deployment draw-identical to a local
buffer makes a resize invisible to the learner.

Failure is atomic: any step aborting
(:class:`~blendjax.replay.shard_client.ReshardAborted`, a dead new
shard, a save that never lands) leaves the ownership map untouched and
the source serving its full range; the half-born shard process is
retired and its disk/shm state swept.  A SIGKILL of the NEW shard
mid-handoff is exactly that abort; a SIGKILL of the SOURCE quarantines
it through the ordinary fault path and the handoff aborts without
touching the map.
"""

from __future__ import annotations

import logging
import time

from blendjax.replay.shard_client import ReshardAborted, ShardRPCError
from blendjax.utils.timing import fleet_counters

logger = logging.getLogger("blendjax")


def reshard_replay(replay, fleet, *, source=None, fraction=0.5,
                   counters=None, timer=None):
    """Add one shard to a live deployment and hand it a slot range.

    Params
    ------
    replay: ShardedReplay
        The draw authority; gains a shard on success.
    fleet: ShardFleet
        The shard processes; ``grow``/``retire`` side of the resize.
    source: int | None
        Live shard surrendering the range; defaults to the shard
        owning the most slots (the one a previous reshard split
        least).
    fraction: float
        Share of the source's owned slots that moves.

    Returns ``(shard_index, address)`` of the adopted shard.  Raises
    :class:`~blendjax.replay.shard_client.ReshardAborted` (map
    untouched, source untouched, newcomer retired) on any failure.
    """
    counters = counters if counters is not None else fleet_counters
    timer = timer if timer is not None else replay.timer
    t0 = time.perf_counter()
    if source is None:
        with replay._cond:
            owned = [
                int((replay._owner == s).sum())
                for s in range(replay.num_shards)
            ]
            dead = replay._dead.copy()
        live = [s for s in range(len(owned)) if not dead[s]]
        if not live:
            raise ReshardAborted(
                f"{replay.name}: no live shard to reshard from"
            )
        source = max(live, key=lambda s: owned[s])
    try:
        cut = replay.clients[source].rpc("save")
    except ShardRPCError as exc:
        counters.incr("autoscale_reshard_aborts")
        raise ReshardAborted(
            f"{replay.name}: source shard {source} save failed: {exc}"
        ) from exc
    idx, addr = fleet.grow(restore_ckpt=cut["path"])
    try:
        shard = replay.adopt_shard(
            addr, source=int(source), cut_seq=int(cut["seq"]),
            fraction=fraction,
        )
    except BaseException:
        # abort WHOLE: the newcomer process (and its disk/shm state)
        # goes away; the map and the source were never touched
        fleet.retire(idx)
        raise
    dt = time.perf_counter() - t0
    timer.add("autoscale_resize", dt, _t0=t0)
    logger.warning(
        "reshard: shard %d live at %s, %d shards serving (%.2fs "
        "decision-to-settle)", shard, addr, replay.num_shards, dt,
    )
    return shard, addr
