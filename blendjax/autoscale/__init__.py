"""Live autoscaling (docs/autoscaling.md): resize the serve fleet and
the replay shard set WHILE the system serves traffic, every transition
verified and reversible.

- :class:`AutoscaleController` — the serve-tier control loop: scraped
  queue depth / p99 drive ``grow``/``drain``/``retire`` decisions with
  hysteresis bands, per-direction cooldowns and a post-action healthy
  window that ROLLS BACK a resize that regressed error rate or latency
  (the :class:`~blendjax.weights.controller.WeightBusController`
  promote/rollback template pointed at capacity instead of weights).
- :func:`reshard_replay` — the replay-tier resize: grow the shard
  fleet by one process and hand it a slot range crash-exactly
  (checkpoint copy + ``written_since`` delta + locked cutover), the
  draw stream never pausing and staying bit-identical over unmoved
  ranges.
"""

from blendjax.autoscale.controller import AutoscaleController
from blendjax.autoscale.reshard import reshard_replay

__all__ = ["AutoscaleController", "reshard_replay"]
