"""Bidirectional PAIR-socket control channel, shared implementation.

The reference ships two near-identical copies (``pkg_pytorch/.../duplex.py``
and ``pkg_blender/.../duplex.py``, differing only in bind-vs-connect at
line 18); blendjax keeps one class and lets each side pick its role:
producer (Blender) binds, consumer connects.

This is the control plane that lets a training loop push new simulation
parameters into running Blender instances mid-training (the densityopt
workflow, reference ``examples/densityopt/densityopt.py:95-107``).
"""

from __future__ import annotations

import zmq

from blendjax import wire


class DuplexChannelBase:
    """PAIR socket with HWM-10 queues and send/recv timeouts.

    Params
    ------
    address: str
        ZMQ endpoint.
    btid: int | None
        Instance id stamped into outgoing messages.
    bind: bool
        Bind (producer side) instead of connect (consumer side).
    lingerms / timeoutms: int
        Socket teardown / send+recv timeouts.
    raw_buffers: bool
        Zero-copy multipart encoding for ndarray payloads.
    """

    def __init__(
        self,
        address,
        btid=None,
        bind=False,
        lingerms=0,
        timeoutms=None,
        raw_buffers=False,
    ):
        if timeoutms is None:
            timeoutms = self.DEFAULT_TIMEOUTMS
        self.btid = btid
        self.raw_buffers = raw_buffers
        self._ctx = zmq.Context.instance()
        self.sock = self._ctx.socket(zmq.PAIR)
        self.sock.setsockopt(zmq.LINGER, lingerms)
        self.sock.setsockopt(zmq.RCVHWM, wire.DEFAULT_HWM)
        self.sock.setsockopt(zmq.SNDHWM, wire.DEFAULT_HWM)
        self.sock.setsockopt(zmq.SNDTIMEO, timeoutms)
        self.sock.setsockopt(zmq.RCVTIMEO, timeoutms)
        if bind:
            self.sock.bind(address)
        else:
            self.sock.connect(address)
        self.poller = zmq.Poller()
        self.poller.register(self.sock, zmq.POLLIN)

    DEFAULT_TIMEOUTMS = 10000

    def recv(self, timeoutms=None):
        """Next message dict, or None when ``timeoutms`` elapses.

        ``timeoutms=None`` blocks; ``0`` polls non-blocking (the producer's
        per-frame pattern, reference ``supershape.blend.py:26-37``).
        """
        if self.poller.poll(timeoutms):
            return wire.recv_message(self.sock)
        return None

    def send(self, **kwargs):
        """Send a message; stamps ``btid`` and a fresh ``btmid`` and returns
        the ``btmid`` for correlating replies (reference ``duplex.py:44-67``)."""
        mid = wire.new_message_id()
        data = {wire.BTID_KEY: self.btid, wire.BTMID_KEY: mid, **kwargs}
        wire.send_message(self.sock, data, raw_buffers=self.raw_buffers)
        return mid

    def close(self):
        self.sock.close(0)
