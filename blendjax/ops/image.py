"""Image ops for the device-side input pipeline.

The hot path of every blendjax workload is: uint8 frames off the wire →
normalized float (optionally linearized) feeding a conv net.  The reference
does its color conversion per-pixel in numpy on the Blender CPU
(``btb/offscreen.py:105-112``, gamma ``pow`` per frame); blendjax ships
uint8 over the wire (4x less bandwidth than float32) and decodes **on the
TPU**, where XLA fuses the conversion into the first convolution.

Two implementations of the decode:

- :func:`decode_frames` — pure jax.numpy; XLA fuses it; the default.
- :func:`decode_frames_pallas` — a Pallas TPU kernel doing
  uint8→float→(sRGB linearize)→normalize in one VMEM pass; useful when the
  decode feeds multiple consumers and you want it materialized exactly
  once.  Runs in interpret mode on CPU for tests.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

# sRGB <-> linear (IEC 61966-2-1)


def srgb_to_linear(x):
    """Decode sRGB-encoded [0,1] floats to linear light."""
    return jnp.where(x <= 0.04045, x / 12.92, ((x + 0.055) / 1.055) ** 2.4)


def linear_to_srgb(x):
    """Encode linear-light [0,1] floats to sRGB (what the reference's
    producer-side ``gamma_coeff=2.2`` approximates)."""
    x = jnp.clip(x, 0.0, 1.0)
    return jnp.where(x <= 0.0031308, x * 12.92, 1.055 * x ** (1 / 2.4) - 0.055)


def decode_frames(frames_u8, dtype=jnp.float32, linearize=False, mean=None, std=None):
    """uint8 [0,255] frames -> normalized ``dtype`` in one fused expression.

    Params
    ------
    frames_u8: uint8 array, any shape (typically NHWC).
    dtype: output dtype (use ``jnp.bfloat16`` to feed MXU convs directly).
    linearize: apply sRGB -> linear decode.
    mean/std: optional per-channel normalization (broadcast over trailing
        channel axis).
    """
    x = frames_u8.astype(jnp.float32) * (1.0 / 255.0)
    if linearize:
        x = srgb_to_linear(x)
    if mean is not None:
        x = x - jnp.asarray(mean, jnp.float32)
    if std is not None:
        x = x / jnp.asarray(std, jnp.float32)
    return x.astype(dtype)


# -- Pallas variant ---------------------------------------------------------

_LANE = 128
_SUBLANE = 32  # uint8 min tile is (32, 128)


def _decode_kernel(x_ref, o_ref, *, linearize):
    # Mosaic has no direct uint8->float32 cast (NotImplementedError at
    # lowering; caught by tests/test_tpu_lowering.py) — widen through
    # int32 first, which both legs support
    x = x_ref[:].astype(jnp.int32).astype(jnp.float32) * (1.0 / 255.0)
    if linearize:
        x = jnp.where(x <= 0.04045, x / 12.92, ((x + 0.055) / 1.055) ** 2.4)
    o_ref[:] = x.astype(o_ref.dtype)


@functools.partial(
    jax.jit, static_argnames=("dtype", "linearize", "block_rows", "interpret")
)
def decode_frames_pallas(
    frames_u8, dtype=jnp.float32, linearize=False, block_rows=256, interpret=False
):
    """Pallas TPU kernel version of :func:`decode_frames` (no mean/std).

    The frame batch is viewed as a 2-D (rows, 128) array padded to the TPU
    tile grid; each grid step converts ``block_rows`` rows HBM->VMEM->HBM.
    ``interpret=True`` runs the kernel in the Pallas interpreter (CPU CI).
    """
    orig_shape = frames_u8.shape
    total = frames_u8.size
    rows = -(-total // _LANE)  # ceil
    pad_rows = -(-rows // _SUBLANE) * _SUBLANE - rows
    padded = jnp.pad(frames_u8.reshape(-1), (0, (rows + pad_rows) * _LANE - total))
    x2d = padded.reshape(rows + pad_rows, _LANE)

    n_rows = x2d.shape[0]
    block_rows = min(block_rows, n_rows)
    # shrink to a divisor of n_rows that keeps sublane alignment
    while n_rows % block_rows:
        block_rows -= _SUBLANE
    block_rows = max(block_rows, _SUBLANE)

    out = pl.pallas_call(
        functools.partial(_decode_kernel, linearize=linearize),
        out_shape=jax.ShapeDtypeStruct(x2d.shape, dtype),
        grid=(n_rows // block_rows,),
        in_specs=[pl.BlockSpec((block_rows, _LANE), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((block_rows, _LANE), lambda i: (i, 0)),
        interpret=interpret,
    )(x2d)
    return out.reshape(-1)[:total].reshape(orig_shape)


def normalize(x, mean, std):
    """(x - mean) / std with broadcasting over the channel axis."""
    return (x - jnp.asarray(mean, x.dtype)) / jnp.asarray(std, x.dtype)
