"""Device-side input-pipeline ops (decode, color, augmentation)."""

from blendjax.ops import augment, image
from blendjax.ops.image import (
    decode_frames,
    decode_frames_pallas,
    linear_to_srgb,
    normalize,
    srgb_to_linear,
)

__all__ = [
    "augment",
    "image",
    "decode_frames",
    "decode_frames_pallas",
    "linear_to_srgb",
    "normalize",
    "srgb_to_linear",
]
