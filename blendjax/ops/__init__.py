"""Device-side input-pipeline ops (decode, color, augmentation)."""

from blendjax.ops import augment, image
from blendjax.ops.flash_attention import flash_attention, make_flash_attention
from blendjax.ops.image import (
    decode_frames,
    decode_frames_pallas,
    linear_to_srgb,
    normalize,
    srgb_to_linear,
)

__all__ = [
    "augment",
    "image",
    "flash_attention",
    "make_flash_attention",
    "decode_frames",
    "decode_frames_pallas",
    "linear_to_srgb",
    "normalize",
    "srgb_to_linear",
]
