"""Device-side input-pipeline ops (decode, color, augmentation) and
inference-efficiency ops (int8 quantization)."""

from blendjax.ops import augment, image, quant
from blendjax.ops.flash_attention import flash_attention, make_flash_attention
from blendjax.ops.image import (
    decode_frames,
    decode_frames_pallas,
    linear_to_srgb,
    normalize,
    srgb_to_linear,
)
from blendjax.ops.quant import (
    detector_apply_int8,
    quantize_detector,
    quantize_seqformer,
)

__all__ = [
    "augment",
    "image",
    "quant",
    "flash_attention",
    "make_flash_attention",
    "decode_frames",
    "decode_frames_pallas",
    "linear_to_srgb",
    "normalize",
    "srgb_to_linear",
    "detector_apply_int8",
    "quantize_detector",
    "quantize_seqformer",
]
