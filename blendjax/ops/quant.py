"""Post-training int8 quantization (w8a8) for inference.

TPU MXUs multiply int8 operands at up to twice the bf16 rate with int32
accumulation, so a quantized forward both halves weight memory and
raises the arithmetic ceiling.  The scheme here is the standard
symmetric one:

- **weights**: per-output-channel symmetric int8 (`round(w / s)`,
  ``s = amax / 127``), quantized once offline by
  :func:`quantize_detector`;
- **activations**: dynamic per-tensor symmetric int8, scale computed
  from the live tensor right before each matmul/conv (no calibration
  pass needed — the extra ``max``/``mul`` is negligible next to the
  conv itself and fuses);
- **accumulation**: int32 (``preferred_element_type``), dequantized by
  ``act_scale * weight_scale`` back to f32 before bias and
  nonlinearity (GELU/sigmoid stay float — quantizing through them
  costs accuracy for no MXU win).

The reference has no quantization story (its models are user-land
torch); this is the TPU-native inference-efficiency counterpart for the
flagship detector.  Parity is tested against the bf16 forward on a
TRAINED model (random weights overstate quantization error), and the
int8 convs' Mosaic lowering is export-proven.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def quantize_tensor(w, reduce_axes):
    """Symmetric int8 quantization of ``w``; the scale is per-slice
    over every axis NOT in ``reduce_axes`` (pass all-but-last for the
    usual per-output-channel scheme).  Returns ``(q int8, scale f32)``
    with ``scale`` keeping reduced dims (broadcastable)."""
    amax = jnp.max(jnp.abs(w.astype(jnp.float32)), axis=reduce_axes,
                   keepdims=True)
    scale = jnp.maximum(amax / 127.0, 1e-8)
    q = jnp.clip(jnp.round(w / scale), -127, 127).astype(jnp.int8)
    return q, scale.astype(jnp.float32)


def quantize_act(x):
    """Dynamic PER-EXAMPLE symmetric int8: ``(q, scale (N,1,...))``.
    A whole-batch scale would couple examples — one high-activation
    outlier coarsens every other image's quantization, making outputs
    depend on batch composition; per-example scales keep inference
    batch-independent (tested) at the same MXU path.  Same quantizer as
    the weights (:func:`quantize_tensor`), reduced over all non-batch
    axes."""
    return quantize_tensor(x, reduce_axes=tuple(range(1, x.ndim)))


def quantize_dense(p):
    """``{'w', 'b'}`` (d_in, d_out) -> int8 params, per-output-column
    scale."""
    q, s = quantize_tensor(p["w"], reduce_axes=(0,))
    return {"w_q": q, "w_scale": s.reshape(-1),
            "b": p["b"].astype(jnp.float32)}


def dense_apply_int8(qp, x):
    """int8 x int8 -> int32 matmul, dequantized to f32 (+ bias)."""
    xq, xs = quantize_act(x)
    acc = lax.dot_general(
        xq, qp["w_q"], (((x.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    )
    return acc.astype(jnp.float32) * (xs * qp["w_scale"]) + qp["b"]


def quantize_conv(p):
    """HWIO conv ``{'w', 'b'}`` -> int8 kernel, per-output-channel
    scale."""
    q, s = quantize_tensor(p["w"], reduce_axes=(0, 1, 2))
    return {"w_q": q, "w_scale": s.reshape(-1),
            "b": p["b"].astype(jnp.float32)}


def conv_apply_int8(qp, x, stride=1, padding="SAME"):
    """int8 x int8 -> int32 NHWC conv, dequantized to f32 (+ bias)."""
    xq, xs = quantize_act(x)
    acc = lax.conv_general_dilated(
        xq, qp["w_q"], window_strides=(stride, stride), padding=padding,
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        preferred_element_type=jnp.int32,
    )
    return acc.astype(jnp.float32) * (xs * qp["w_scale"]) + qp["b"]


def _x_contracted_axes(eq, x_ndim):
    """Axes of the FIRST einsum operand that are contracted away (their
    labels don't reach the output) — exactly the axes an activation
    scale must be constant over for dequantization to be exact."""
    lhs, out = eq.split("->")
    xspec = lhs.split(",")[0]
    if "..." in xspec:
        head, tail = xspec.split("...")
        if head:
            # labels BEFORE the ellipsis would need leading-axis index
            # math this helper doesn't do — reject loudly rather than
            # silently mis-scale (no call site needs the form)
            raise NotImplementedError(
                f"einsum spec {xspec!r}: put named x labels after '...'"
            )
        offset = x_ndim - len(tail)
        return tuple(offset + i for i, c in enumerate(tail)
                     if c not in out)
    return tuple(i for i, c in enumerate(xspec) if c not in out)


def maybe_quantized_einsum(eq, x, p, dtype):
    """``einsum(eq, x, w)`` that dispatches on the weight dict: float
    (``{'w'}``) runs in ``dtype``; quantized (``{'w_q', 'w_scale'}``)
    quantizes ``x`` with one scale per OUTPUT-surviving coordinate —
    i.e. reduced over exactly the contracted axes (a scale varying
    within a contraction could not be factored out of the int32 sum,
    and a scale pooled over kept axes like the sequence would let
    future positions change a past token's quantization, breaking
    causality and prefill/decode parity) — int8-einsums to int32, and
    dequantizes by running the SAME einsum over the keepdims scales
    (contracted scale axes have size 1, so the 'sum' is exactly the
    product of scales: one rule for every equation)."""
    if "w_q" not in p:
        return jnp.einsum(eq, x, p["w"].astype(dtype))
    if p["w_scale"].ndim != p["w_q"].ndim:
        # two scale-shape conventions coexist in this module:
        # quantize_seqformer keeps KEEPDIMS scales (required here — the
        # dequant einsum needs the scale to broadcast like the weight),
        # while quantize_dense/quantize_conv flatten to reshape(-1) for
        # the detector's apply kernels.  Mixing them used to surface as
        # an opaque einsum ndim mismatch (ADVICE r5) — name it instead.
        raise ValueError(
            f"maybe_quantized_einsum needs keepdims weight scales "
            f"(w_scale.ndim == w_q.ndim == {p['w_q'].ndim}, got "
            f"w_scale.ndim {p['w_scale'].ndim}): this dict looks like a "
            "detector-style quantization (quantize_dense/quantize_conv "
            "flatten scales with reshape(-1) for the conv/dense apply "
            "kernels); quantize with quantize_seqformer-style keepdims "
            "scales (quantize_tensor output, unreshaped) for einsum use"
        )
    xq, xs = quantize_tensor(x, reduce_axes=_x_contracted_axes(eq, x.ndim))
    acc = jnp.einsum(eq, xq, p["w_q"], preferred_element_type=jnp.int32)
    scale = jnp.einsum(eq, xs, p["w_scale"])
    return acc.astype(jnp.float32) * scale


def quantize_seqformer(params):
    """Offline PTQ of a :mod:`blendjax.models.seqformer` pytree for
    INFERENCE (:func:`seqformer.apply` / :func:`seqformer.rollout`):
    attention projections, MLP, embed, and head go w8 (per-output
    scales); layernorms, biases, the pos table, and MoE blocks (gate
    routing is precision-sensitive) stay f32.

    The quantized pytree keeps the model's STRUCTURE (each ``{'w'}``
    becomes ``{'w_q', 'w_scale', 'b'}``), and the forward dispatches per
    weight dict (:func:`maybe_quantized_einsum`), so the same model code
    serves both precisions."""

    def qd(p, reduce_axes):
        q, s = quantize_tensor(p["w"], reduce_axes)
        return {"w_q": q, "w_scale": s,
                "b": p["b"].astype(jnp.float32)}

    out = {
        "embed": qd(params["embed"], (0,)),
        "head": qd(params["head"], (0,)),
        "ln_f": params["ln_f"],
        "blocks": [],
    }
    if "pos" in params:
        out["pos"] = params["pos"]
    for blk in params["blocks"]:
        qb = {
            "ln1": blk["ln1"],
            "ln2": blk["ln2"],
            "wq": qd(blk["wq"], (0,)),
            "wk": qd(blk["wk"], (0,)),
            "wv": qd(blk["wv"], (0,)),
            "wo": qd(blk["wo"], (0, 1)),
        }
        if "mlp" in blk:
            qb["mlp"] = {
                "fc": qd(blk["mlp"]["fc"], (0,)),
                "proj": qd(blk["mlp"]["proj"], (0,)),
            }
        if "moe" in blk:
            qb["moe"] = blk["moe"]
        out["blocks"].append(qb)
    return out


def quantize_policy(params):
    """Offline PTQ of a :mod:`blendjax.models.policy` MLP pytree for
    INFERENCE serving: every dense layer goes w8 (per-output-column
    scales, the :func:`quantize_dense`/:func:`dense_apply_int8` pair);
    the Gaussian head's ``log_std`` stays f32.  ``policy.logits``
    dispatches per weight dict, so the same policy code serves both
    precisions (the ``blendjax/serve`` ``--int8`` path)."""
    out = {
        "layers": [quantize_dense(p) for p in params["layers"]],
        "out": quantize_dense(params["out"]),
    }
    if "log_std" in params:
        out["log_std"] = params["log_std"]
    return out


def quantize_for_wire(params, kind):
    """Model-kind dispatch over the offline PTQ entry points — the
    :mod:`blendjax.weights` publisher's wire quantizer: attention/MLP/
    head weights ship int8 (quarter the snapshot bytes), while the
    leaves each quantizer deliberately keeps float (layernorms, biases,
    position tables, MoE gates — precision-sensitive) ride the float
    fallback unchanged.  ``kind=None`` is the identity (float wire)."""
    if kind is None:
        return params
    if kind == "seqformer":
        return quantize_seqformer(params)
    if kind == "policy":
        return quantize_policy(params)
    if kind == "detector":
        return quantize_detector(params)
    raise ValueError(
        f"unknown wire-quantization kind {kind!r}; expected one of "
        "seqformer/policy/detector or None"
    )


def quantize_detector(params):
    """Offline PTQ of a trained :mod:`blendjax.models.detector` pytree:
    every conv and dense layer goes w8; biases stay f32."""
    return {
        "convs": [quantize_conv(c) for c in params["convs"]],
        "fc": quantize_dense(params["fc"]),
        "head": quantize_dense(params["head"]),
    }


def detector_apply_int8(qparams, images):
    """Quantized detector forward: THE SAME :func:`detector.apply` body
    with the int8 layer kernels injected through its conv_fn/dense_fn
    seams (one source of truth — an architecture edit cannot silently
    leave this mirror computing the old network), f32 GELU/pool/sigmoid
    between them.  images (N, H, W, C) float in [0, 1] -> (N, K, 2)."""
    from blendjax.models import detector

    return detector.apply(
        qparams, images, compute_dtype=jnp.float32,
        conv_fn=lambda p, x, stride: conv_apply_int8(p, x, stride=stride),
        dense_fn=dense_apply_int8,
    )
