"""Device-side data augmentation, jit/vmap-friendly.

Domain randomization happens producer-side in Blender (pose/material/light
randomization in the ``*.blend.py`` scripts); these ops add cheap
consumer-side augmentation on the TPU, keyed by explicit PRNG keys so the
whole input pipeline stays functional and reproducible.  All shapes are
static (crops use ``lax.dynamic_slice`` with static sizes) so everything
compiles once.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def random_hflip(key, images, keypoints_xy=None):
    """Flip a NHWC batch horizontally with per-sample probability 0.5.

    When pixel-space ``keypoints_xy`` (N, K, 2) are given they are flipped
    consistently and returned alongside.
    """
    n = images.shape[0]
    w = images.shape[2]
    flip = jax.random.bernoulli(key, 0.5, (n,))
    flipped = jnp.where(flip[:, None, None, None], images[:, :, ::-1, :], images)
    if keypoints_xy is None:
        return flipped
    kx = jnp.where(flip[:, None], w - 1 - keypoints_xy[..., 0], keypoints_xy[..., 0])
    kps = jnp.stack([kx, keypoints_xy[..., 1]], axis=-1)
    return flipped, kps


def random_crop(key, images, crop_hw):
    """Random spatial crop of a NHWC batch to static (ch, cw)."""
    n, h, w, c = images.shape
    ch, cw = crop_hw
    ky, kx = jax.random.split(key)
    tops = jax.random.randint(ky, (n,), 0, h - ch + 1)
    lefts = jax.random.randint(kx, (n,), 0, w - cw + 1)

    def crop_one(img, top, left):
        return lax.dynamic_slice(img, (top, left, 0), (ch, cw, c))

    return jax.vmap(crop_one)(images, tops, lefts)


def random_brightness(key, images, max_delta=0.2):
    """Additive brightness jitter on [0,1] float images."""
    n = images.shape[0]
    delta = jax.random.uniform(key, (n, 1, 1, 1), minval=-max_delta, maxval=max_delta)
    return jnp.clip(images + delta, 0.0, 1.0)


def random_contrast(key, images, lower=0.8, upper=1.2):
    """Multiplicative contrast jitter around the per-image mean."""
    n = images.shape[0]
    factor = jax.random.uniform(key, (n, 1, 1, 1), minval=lower, maxval=upper)
    mean = images.mean(axis=(1, 2, 3), keepdims=True)
    return jnp.clip((images - mean) * factor + mean, 0.0, 1.0)
