"""Pallas TPU flash attention: block-wise online-softmax attention that
never materializes the (T, T) score matrix.

The SeqFormer's single-device attention (`full_attention`,
``blendjax/parallel/ring_attention.py``) builds (B, H, T, T) scores —
O(T^2) HBM traffic and memory, the classic long-context wall.  This
kernel streams K/V blocks through VMEM, keeping the running max/sum and
the output accumulator on-chip (the FlashAttention recurrence), so HBM
traffic is O(T*D) and the MXU sees back-to-back (block_q, D) x
(D, block_kv) and (block_q, block_kv) x (block_kv, D) matmuls.

Grid layout: ``(B*H, T/block_q, T/block_kv)`` with the KV dimension
innermost — TPU grid steps run sequentially per core, so the f32
accumulator/max/sum scratch carries across KV steps and is written to
the output on the last one.

Differentiation: the forward is the fused kernel; the backward currently
recomputes attention through the reference einsum path (``custom_vjp``)
— gradients are exact, the O(T^2) memory returns only inside the
backward, and ``jax.checkpoint`` around the call keeps training memory
flat.  A fused backward kernel is the natural next step.

Interpret mode (``interpret=True``) runs the same kernel on CPU for CI;
parity against ``full_attention`` is tested both causal and not.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # TPU-specific memory spaces; absent in CPU-only builds
    from jax.experimental.pallas import tpu as pltpu

    _VMEM = pltpu.VMEM
except Exception:  # pragma: no cover
    pltpu = None
    _VMEM = None

_NEG = -1e30


def _kernel(q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref, *,
            scale, causal, block_q, block_kv, num_kv):
    j = pl.program_id(2)

    @pl.when(j == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, _NEG)
        l_ref[...] = jnp.zeros_like(l_ref)

    q = q_ref[0].astype(jnp.float32)  # (block_q, d)
    k = k_ref[0].astype(jnp.float32)  # (block_kv, d)
    v = v_ref[0].astype(jnp.float32)

    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    ) * scale  # (block_q, block_kv)

    if causal:
        i = pl.program_id(1)
        rows = i * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_kv), 0
        )
        cols = j * block_kv + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_kv), 1
        )
        s = jnp.where(cols <= rows, s, _NEG)

    m_prev = m_ref[:, :1]
    l_prev = l_ref[:, :1]
    m_cur = jnp.maximum(m_prev, s.max(axis=-1, keepdims=True))
    p = jnp.exp(s - m_cur)
    alpha = jnp.exp(m_prev - m_cur)
    l_new = alpha * l_prev + p.sum(axis=-1, keepdims=True)
    acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot_general(
        p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )
    m_ref[...] = jnp.broadcast_to(m_cur, m_ref.shape)
    l_ref[...] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(j == num_kv - 1)
    def _emit():
        l = l_ref[:, :1]
        o_ref[0] = (acc_ref[...] / jnp.where(l == 0.0, 1.0, l)).astype(
            o_ref.dtype
        )


def _flash_fwd(q, k, v, causal, scale, block_q, block_kv, interpret):
    b, t, h, d = q.shape
    if t % block_q or t % block_kv:
        raise ValueError(
            f"sequence length {t} must divide block_q={block_q} and "
            f"block_kv={block_kv} (pad upstream or pick smaller blocks)"
        )
    qf = q.transpose(0, 2, 1, 3).reshape(b * h, t, d)
    kf = k.transpose(0, 2, 1, 3).reshape(b * h, t, d)
    vf = v.transpose(0, 2, 1, 3).reshape(b * h, t, d)
    num_q = t // block_q
    num_kv = t // block_kv

    kernel = functools.partial(
        _kernel, scale=scale, causal=causal, block_q=block_q,
        block_kv=block_kv, num_kv=num_kv,
    )
    if _VMEM is not None:
        scratch = [
            _VMEM((block_q, d), jnp.float32),
            _VMEM((block_q, 128), jnp.float32),
            _VMEM((block_q, 128), jnp.float32),
        ]
    else:  # pragma: no cover - jaxlib without the TPU pallas extension
        scratch = [
            jax.ShapeDtypeStruct((block_q, d), jnp.float32),
            jax.ShapeDtypeStruct((block_q, 128), jnp.float32),
            jax.ShapeDtypeStruct((block_q, 128), jnp.float32),
        ]
    kwargs = {"scratch_shapes": scratch}
    out = pl.pallas_call(
        kernel,
        grid=(b * h, num_q, num_kv),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, i, j: (bh, i, 0)),
            pl.BlockSpec((1, block_kv, d), lambda bh, i, j: (bh, j, 0)),
            pl.BlockSpec((1, block_kv, d), lambda bh, i, j: (bh, j, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda bh, i, j: (bh, i, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, t, d), q.dtype),
        interpret=interpret,
        **kwargs,
    )(qf, kf, vf)
    return out.reshape(b, h, t, d).transpose(0, 2, 1, 3)


@functools.partial(
    jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7)
)
def flash_attention(q, k, v, causal=False, scale=None, block_q=128,
                    block_kv=128, interpret=False):
    """Fused block-wise attention; same contract as ``full_attention``:
    q/k/v (B, T, H, D) -> (B, T, H, D).

    ``T`` must divide by both block sizes (pick blocks accordingly or pad
    upstream).  ``interpret=True`` runs on CPU (CI parity tests).
    """
    scale = scale if scale is not None else 1.0 / (q.shape[-1] ** 0.5)
    return _flash_fwd(q, k, v, causal, scale, block_q, block_kv, interpret)


def _ref(q, k, v, causal, scale):
    from blendjax.parallel.ring_attention import full_attention

    return full_attention(q, k, v, causal=causal, scale=scale)


def _fwd(q, k, v, causal, scale, block_q, block_kv, interpret):
    out = flash_attention(
        q, k, v, causal, scale, block_q, block_kv, interpret
    )
    return out, (q, k, v)


def _bwd(causal, scale, block_q, block_kv, interpret, res, g):
    q, k, v = res
    scale = scale if scale is not None else 1.0 / (q.shape[-1] ** 0.5)
    _, vjp = jax.vjp(lambda q, k, v: _ref(q, k, v, causal, scale), q, k, v)
    return vjp(g)


flash_attention.defvjp(_fwd, _bwd)


def make_flash_attention(causal=True, block_q=128, block_kv=128,
                         interpret=False):
    """``attn_fn`` closure for :func:`blendjax.models.seqformer.apply` —
    drop-in for the default ``full_attention`` when T divides the block
    sizes."""

    def attn(q, k, v):
        return flash_attention(
            q, k, v, causal, None, block_q, block_kv, interpret
        )

    return attn
