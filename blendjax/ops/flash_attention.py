"""Pallas TPU flash attention: block-wise online-softmax attention that
never materializes the (T, T) score matrix.

The SeqFormer's single-device attention (`full_attention`,
``blendjax/parallel/ring_attention.py``) builds (B, H, T, T) scores —
O(T^2) HBM traffic and memory, the classic long-context wall.  This
kernel streams K/V blocks through VMEM, keeping the running max/sum and
the output accumulator on-chip (the FlashAttention recurrence), so HBM
traffic is O(T*D) and the MXU sees back-to-back (block_q, D) x
(D, block_kv) and (block_q, block_kv) x (block_kv, D) matmuls.

Grid layout: ``(B*H, T/block_q, T/block_kv)`` with the KV dimension
innermost — TPU grid steps run sequentially per core, so the f32
accumulator/max/sum scratch carries across KV steps and is written to
the output on the last one.

Differentiation is fully fused too (``custom_vjp``): the forward also
emits the per-row logsumexp, and the backward runs two block-wise
kernels — a dQ pass (KV innermost, dQ accumulator carried) and a dK/dV
pass (Q innermost) — recomputing probabilities from the saved logsumexp
(FlashAttention-2 recurrence, with ``D = rowsum(dO * O)`` as the
softmax-jacobian correction).  No (T, T) matrix exists in either
direction; gradient parity vs the einsum reference is tested to ~5e-5.

Interpret mode (``interpret=True``) runs the same kernel on CPU for CI;
parity against ``full_attention`` is tested both causal and not.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # TPU-specific memory spaces; absent in CPU-only builds
    from jax.experimental.pallas import tpu as pltpu

    _VMEM = pltpu.VMEM
except Exception:  # pragma: no cover
    pltpu = None
    _VMEM = None

_NEG = -1e30


def _default_scale(scale, d):
    return scale if scale is not None else 1.0 / (d ** 0.5)


def flash_block_size(seq_len):
    """Largest flash tile dividing ``seq_len`` (or ``seq_len`` itself —
    legal on TPU via the 'equal to the array dim' tiling clause).  THE
    tile-selection policy, shared by the ring/ulysses parallel paths and
    user code sizing the kernel for arbitrary sequence lengths."""
    return next((b for b in (128, 64, 32) if seq_len % b == 0), seq_len)


def _block_live(causal, qi, kj, block_q, block_kv, window=None,
                q_offset=0):
    """False for blocks whose probabilities are exactly zero, so compute
    is skipped: strictly above the causal diagonal (roughly halves the
    FLOPs at long context), and — under a sliding ``window`` — strictly
    below it (every key older than ``window`` positions).  The windowed
    grids are also *shrunk* (see ``_kv_window_steps``): ``kj``/``qi``
    may then be derived block indices that run past the array, and the
    two predicates below also correctly kill those overshoot steps (a
    too-large ``kj`` fails the causal bound when ``q_offset == 0``; a
    too-large ``qi`` fails the window bound) — EXCEPT a kv overshoot
    under a nonzero ``q_offset``, where rows sit above every real
    column and the caller's kernels add an explicit range guard.

    ``q_offset`` (static) is the q rows' global position minus the kv
    cols': the ring variants run this kernel on (my queries x an
    EARLIER shard's KV), where the pair's offset is a static multiple
    of the shard length."""
    if not causal:
        return True
    live = kj * block_kv <= qi * block_q + q_offset + (block_q - 1)
    if window is not None:
        # kv block's newest col must be within `window` of the q block's
        # oldest row: max_col >= min_row - (window - 1).  qi/kj are traced
        # program ids, so combine with logical_and, not `and`
        live = jnp.logical_and(
            live,
            kj * block_kv + (block_kv - 1)
            >= qi * block_q + q_offset - (window - 1),
        )
    return live


def _kv_window_steps(num_kv, block_q, block_kv, window):
    """Grid steps needed along KV for one q block under a sliding window:
    the visible span is ``block_q + window - 1`` contiguous positions,
    which straddles at most ``(span - 2) // block_kv + 2`` KV blocks at
    worst-case alignment.  This is what makes windowed attention O(T*W)
    in *grid steps and HBM traffic*, not just FLOPs — without it the
    grid stays (bh, T/bq, T/bkv) and every dead block still costs a DMA
    and a grid step."""
    span = block_q + window - 1
    return min(num_kv, (span - 2) // block_kv + 2)


def _kv_base(i, block_q, block_kv, window, q_offset=0):
    """First KV block index visible to q block ``i`` (floor-clamped to
    0); traced — used in both the BlockSpec index maps and the kernels'
    liveness checks."""
    return jnp.maximum(
        0, (i * block_q + q_offset - (window - 1)) // block_kv
    )


def _q_window_steps(num_q, block_q, block_kv, window):
    """Grid steps along Q for one KV block in the dK/dV pass (rows that
    can see this block span ``block_kv + window - 1`` positions)."""
    span = block_kv + window - 1
    return min(num_q, (span - 2) // block_q + 2)


def _q_base(j, block_q, block_kv, window, q_offset=0):
    """First Q block index that can see KV block ``j`` (causal: rows
    start at the block's own first column, shifted down by the pair's
    static row/col offset)."""
    del window
    return jnp.maximum(0, (j * block_kv - q_offset) // block_q)


def _window_index_map(num_blocks, base_fn, head_map=None):
    """BlockSpec index map for a shrunk windowed grid axis: the inner
    grid step maps to block ``base(mid) + step``, clamped onto the last
    real block (overshoot steps' compute is killed by the kernels'
    liveness predicates; the clamped DMA is the only waste).  Every
    pass's windowed axis has this shape — fwd/dQ run ``(bh, q, kv)``
    with the KV base driven by the q index, dK/dV runs ``(bh, kv, q)``
    with the Q base driven by the kv index — so one helper keeps the
    three derivations from desynchronizing.  ``head_map`` remaps the
    flat batch*head coordinate (GQA: several q heads share a kv head)."""

    def index_map(bh, mid, inner):
        b = bh if head_map is None else head_map(bh)
        return (b, jnp.minimum(base_fn(mid) + inner, num_blocks - 1), 0)

    return index_map


def _kv_head_map(h_q, h_kv):
    """Flat ``b*h`` index of the KV head serving flat q-head ``bh`` —
    grouped-query attention's whole mechanism at the BlockSpec level:
    ``h_q // h_kv`` consecutive q heads read the same KV block, so the
    kernel bodies never know GQA exists.  Identity (None) when the head
    counts match."""
    if h_q == h_kv:
        return None
    g = h_q // h_kv
    return lambda bh: (bh // h_q) * h_kv + (bh % h_q) // g


def _kv_axis(num_kv, block_q, block_kv, window, q_offset, khm):
    """(steps, index map) for the KV grid axis of the fwd and dQ passes
    — the ONE place the windowed-shrink and GQA head-remap derivations
    combine, so the two passes cannot desynchronize."""
    if window is None:
        if khm is None:
            im = lambda bh, i, j: (bh, j, 0)
        else:
            im = lambda bh, i, j: (khm(bh), j, 0)
        return num_kv, im
    steps = _kv_window_steps(num_kv, block_q, block_kv, window)
    im = _window_index_map(
        num_kv,
        lambda i: _kv_base(i, block_q, block_kv, window, q_offset),
        head_map=khm,
    )
    return steps, im


def _mask(s, i, j, block_q, block_kv, window=None, q_offset=0):
    rows = q_offset + i * block_q + jax.lax.broadcasted_iota(
        jnp.int32, s.shape, 0
    )
    cols = j * block_kv + jax.lax.broadcasted_iota(
        jnp.int32, s.shape, 1
    )
    keep = cols <= rows
    if window is not None:
        keep = jnp.logical_and(keep, cols > rows - window)
    return jnp.where(keep, s, _NEG)


def _scores(q_ref, k_ref, qi, kj, scale, causal, block_q, block_kv,
            window=None, q_offset=0):
    q = q_ref[0].astype(jnp.float32)
    k = k_ref[0].astype(jnp.float32)
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    ) * scale
    if causal:
        s = _mask(s, qi, kj, block_q, block_kv, window, q_offset)
    return q, k, s


def _kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref, m_ref, l_ref, *,
            scale, causal, block_q, block_kv, num_kv, num_kv_total=None,
            window=None, q_offset=0):
    j = pl.program_id(2)

    @pl.when(j == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, _NEG)
        l_ref[...] = jnp.zeros_like(l_ref)

    i = pl.program_id(1)
    # under a window the grid's kv axis is shrunk: step j maps to actual
    # kv block base(i) + j (overshoot steps are killed by _block_live)
    kj = j if window is None else _kv_base(
        i, block_q, block_kv, window, q_offset
    ) + j
    live = _block_live(causal, i, kj, block_q, block_kv, window, q_offset)
    if window is not None and q_offset:
        # with rows offset above every real column the causal bound no
        # longer kills a kv overshoot past the array — guard explicitly
        live = jnp.logical_and(live, kj <= num_kv_total - 1)

    @pl.when(live)
    def _compute():
        _, _, s = _scores(q_ref, k_ref, i, kj, scale, causal, block_q,
                          block_kv, window, q_offset)
        v = v_ref[0].astype(jnp.float32)
        m_prev = m_ref[:, :1]
        l_prev = l_ref[:, :1]
        m_cur = jnp.maximum(m_prev, s.max(axis=-1, keepdims=True))
        p = jnp.exp(s - m_cur)
        alpha = jnp.exp(m_prev - m_cur)
        l_new = alpha * l_prev + p.sum(axis=-1, keepdims=True)
        acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_ref[...] = jnp.broadcast_to(m_cur, m_ref.shape)
        l_ref[...] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(j == num_kv - 1)
    def _emit():
        l = l_ref[:, :1]
        safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_ref[...] / safe).astype(o_ref.dtype)
        # lse rides as (bh, t, 1) — a (block_q, 1) block keeps the
        # Mosaic tiling rule (last two block dims divisible by (8, 128)
        # or equal to the array dims); a flat (1, block_q) lse block is
        # rejected by the TPU lowering (caught by the tpu-platform
        # export test, tests/test_tpu_lowering.py)
        lse_ref[0] = m_ref[:, :1] + jnp.log(safe)


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
               dq_acc, *, scale, causal, block_q, block_kv, num_kv,
               num_kv_total=None, window=None, q_offset=0):
    j = pl.program_id(2)

    @pl.when(j == 0)
    def _init():
        dq_acc[...] = jnp.zeros_like(dq_acc)

    i = pl.program_id(1)
    kj = j if window is None else _kv_base(
        i, block_q, block_kv, window, q_offset
    ) + j
    live = _block_live(causal, i, kj, block_q, block_kv, window, q_offset)
    if window is not None and q_offset:
        live = jnp.logical_and(live, kj <= num_kv_total - 1)

    @pl.when(live)
    def _compute():
        _, k, s = _scores(q_ref, k_ref, i, kj, scale, causal, block_q,
                          block_kv, window, q_offset)
        v = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        p = jnp.exp(s - lse_ref[0].astype(jnp.float32))  # (bq,1) bcast
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta_ref[0].astype(jnp.float32)) * scale
        dq_acc[...] += jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(j == num_kv - 1)
    def _emit():
        dq_ref[0] = dq_acc[...].astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref,
                dv_ref, dk_acc, dv_acc, *, scale, causal, block_q, block_kv,
                num_q, num_q_total=None, window=None, q_offset=0):
    i = pl.program_id(2)  # q-block index is INNERMOST in the dkv pass

    @pl.when(i == 0)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    j = pl.program_id(1)
    qi = i if window is None else _q_base(
        j, block_q, block_kv, window, q_offset
    ) + i
    live = _block_live(causal, qi, j, block_q, block_kv, window, q_offset)
    if window is not None:
        # unlike KV overshoot (killed by the causal bound at zero
        # offset), a derived qi past the last real q block still passes
        # both predicates when the window span runs off the end of the
        # sequence — and would double-count the clamped block under a
        # phantom-row mask
        live = jnp.logical_and(live, qi <= num_q_total - 1)

    @pl.when(live)
    def _compute():
        q, _, s = _scores(q_ref, k_ref, qi, j, scale, causal, block_q,
                          block_kv, window, q_offset)
        v = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        p = jnp.exp(s - lse_ref[0].astype(jnp.float32))  # (bq,1) bcast
        dv_acc[...] += jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta_ref[0].astype(jnp.float32)) * scale
        dk_acc[...] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(i == num_q - 1)
    def _emit():
        dk_ref[0] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[...].astype(dv_ref.dtype)


def _scratch(shapes):
    if _VMEM is not None:
        return [_VMEM(s, jnp.float32) for s in shapes]
    # pragma: no cover - jaxlib without the TPU pallas extension
    return [jax.ShapeDtypeStruct(s, jnp.float32) for s in shapes]


def _flat(x):
    b, t, h, d = x.shape
    return x.transpose(0, 2, 1, 3).reshape(b * h, t, d)


def _unflat(xf, b, h):
    bh, t, d = xf.shape
    return xf.reshape(b, h, t, d).transpose(0, 2, 1, 3)


def _sds(shape, dtype, like):
    """ShapeDtypeStruct that inherits ``like``'s varying-manual-axes type,
    so the kernel composes inside shard_map (e.g. as Ulysses' inner
    attention) under vma typing."""
    try:
        vma = jax.typeof(like).vma
        if vma:
            return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    except (AttributeError, TypeError):
        pass
    return jax.ShapeDtypeStruct(shape, dtype)


def _check_blocks(t, block, name):
    if t % block:
        raise ValueError(
            f"sequence length {t} must divide {name}={block} "
            "(pad upstream or pick smaller blocks)"
        )


def _flash_fwd_impl(q, k, v, causal, scale, block_q, block_kv, interpret,
                    out_dtype=None, window=None, q_offset=0):
    """Returns (out (B,T,H,D), flat residuals (qf,kf,vf,of,lse)).

    ``out_dtype`` overrides the output dtype (default: q's) — ring_flash
    requests f32 so its cross-block combination accumulates unrounded
    partials (the kernel's internal accumulator is f32 regardless).
    ``q_offset`` (static): global position of q row 0 minus kv col 0 —
    the windowed ring variant runs this on (my queries x an earlier
    shard's KV) where the offset is a static shard multiple; k/v may
    then have a different sequence length than q.

    GQA: k/v may carry fewer heads than q (``h % h_kv == 0``); the KV
    BlockSpecs then map each q head onto its group's shared KV head."""
    b, t, h, d = q.shape
    tk, h_kv = k.shape[1], k.shape[2]
    _check_blocks(t, block_q, "block_q")
    _check_blocks(tk, block_kv, "block_kv")
    _check_window_overshoot(window, q_offset, t, tk)
    if h % h_kv:
        raise ValueError(
            f"q heads {h} must be a multiple of kv heads {h_kv} (GQA)"
        )
    if v.shape[2] != h_kv:
        raise ValueError(
            f"k has {h_kv} heads but v has {v.shape[2]} — the shared "
            "KV head map would silently read wrong v blocks"
        )
    qf, kf, vf = _flat(q), _flat(k), _flat(v)
    num_q = t // block_q
    num_kv = tk // block_kv
    khm = _kv_head_map(h, h_kv)
    kv_steps, kv_im = _kv_axis(
        num_kv, block_q, block_kv, window, q_offset, khm
    )

    kernel = functools.partial(
        _kernel, scale=scale, causal=causal, block_q=block_q,
        block_kv=block_kv, num_kv=kv_steps, num_kv_total=num_kv,
        window=window, q_offset=q_offset,
    )
    of, lse = pl.pallas_call(
        kernel,
        grid=(b * h, num_q, kv_steps),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, i, j: (bh, i, 0)),
            pl.BlockSpec((1, block_kv, d), kv_im),
            pl.BlockSpec((1, block_kv, d), kv_im),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, i, j: (bh, i, 0)),
            # (bh, t, 1): a (block_q, 1) trailing block satisfies the
            # Mosaic (8, 128)-or-equal tiling rule; (1, block_q) doesn't
            pl.BlockSpec((1, block_q, 1), lambda bh, i, j: (bh, i, 0)),
        ],
        out_shape=[
            _sds((b * h, t, d), out_dtype or q.dtype, qf),
            _sds((b * h, t, 1), jnp.float32, qf),
        ],
        scratch_shapes=_scratch([
            (block_q, d), (block_q, 128), (block_q, 128)
        ]),
        interpret=interpret,
    )(qf, kf, vf)
    return _unflat(of, b, h), (qf, kf, vf, of, lse)


def _check_window(causal, window):
    if window is None:
        return
    if not causal:
        raise ValueError("window (sliding-window attention) requires causal=True")
    if window < 1:
        raise ValueError(f"window must be >= 1, got {window}")


def _check_window_overshoot(window, q_offset, tq, tk):
    """Enforce the windowed-overshoot invariant the kernels rely on: a
    clamped last-KV-block overshoot at ``q_offset == 0`` is only killed
    by the causal bound when ``Tk == Tq`` (true for every current call
    site — full sequences and same-shard ring pairs).  ``Tk != Tq`` with
    a zero offset would read the clamped block with a LIVE mask and
    silently attend out of window, so fail loudly instead (ADVICE r5)."""
    if window is not None and not q_offset and tk != tq:
        raise ValueError(
            f"windowed attention with q_offset=0 requires Tk == Tq (got "
            f"Tq={tq}, Tk={tk}): the overshoot clamp relies on the causal "
            "bound to kill the last KV block, which only holds for "
            "same-length pairs; pass the pair's static q_offset"
        )


@functools.partial(
    jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8)
)
def flash_attention(q, k, v, causal=False, scale=None, block_q=128,
                    block_kv=128, interpret=False, window=None):
    """Fused block-wise attention; same contract as ``full_attention``:
    q/k/v (B, T, H, D) -> (B, T, H, D).

    ``T`` must divide by both block sizes (pick blocks accordingly or pad
    upstream).  ``interpret=True`` runs on CPU (CI parity tests).

    GQA/MQA: k/v may carry FEWER heads than q (``H % H_kv == 0``) —
    each group of ``H // H_kv`` q heads reads the same KV head, purely
    through the KV BlockSpec index maps (kernel bodies are unchanged,
    and KV HBM traffic drops by the group factor); dK/dV group-sums
    per-q-head f32 partials onto the shared head.

    ``window=W`` (requires ``causal=True``) is sliding-window attention:
    each query attends to its own and the previous ``W - 1`` positions.
    ``W`` is static, so every pass (forward, dQ, dK/dV) *shrinks its
    grid*: the KV (resp. Q) axis runs only the ~``W / block`` blocks a
    block can see, with a per-block base offset in the BlockSpec index
    map — grid steps, DMA traffic, and FLOPs all scale O(T*W) instead
    of O(T^2/2).
    """
    _check_window(causal, window)
    scale = _default_scale(scale, q.shape[-1])
    out, _ = _flash_fwd_impl(q, k, v, causal, scale, block_q, block_kv,
                             interpret, window=window)
    return out


def _fwd(q, k, v, causal, scale, block_q, block_kv, interpret, window):
    _check_window(causal, window)
    scale_v = _default_scale(scale, q.shape[-1])
    out, res = _flash_fwd_impl(
        q, k, v, causal, scale_v, block_q, block_kv, interpret,
        window=window
    )
    return out, res + (q.shape,)


def _dq_pass(qf, kf, vf, dof, lse, delta, causal, scale, block_q,
             block_kv, interpret, out_dtype=None, window=None,
             q_offset=0, heads=None):
    """dQ for one (Tq, Tk) pair of flat arrays — used over the full
    sequence by :func:`flash_attention`'s vjp and per ring-block pair by
    :func:`blendjax.parallel.ring_attention.ring_flash_attention` (which
    passes ``out_dtype=f32`` so its cross-block accumulation never sums
    rounded partials)."""
    bh, tq, d = qf.shape
    tk = kf.shape[1]
    _check_window_overshoot(window, q_offset, tq, tk)
    num_q, num_kv = tq // block_q, tk // block_kv
    khm = _kv_head_map(*heads) if heads else None
    kv_steps, kv_im = _kv_axis(
        num_kv, block_q, block_kv, window, q_offset, khm
    )
    q_spec_i = pl.BlockSpec((1, block_q, d), lambda bh, i, j: (bh, i, 0))
    kv_spec_j = pl.BlockSpec((1, block_kv, d), kv_im)
    row_spec_i = pl.BlockSpec((1, block_q, 1), lambda bh, i, j: (bh, i, 0))
    return pl.pallas_call(
        functools.partial(
            _dq_kernel, scale=scale, causal=causal, block_q=block_q,
            block_kv=block_kv, num_kv=kv_steps, num_kv_total=num_kv,
            window=window, q_offset=q_offset,
        ),
        grid=(bh, num_q, kv_steps),
        in_specs=[q_spec_i, kv_spec_j, kv_spec_j, q_spec_i, row_spec_i,
                  row_spec_i],
        out_specs=q_spec_i,
        out_shape=_sds((bh, tq, d), out_dtype or qf.dtype, qf),
        scratch_shapes=_scratch([(block_q, d)]),
        interpret=interpret,
    )(qf, kf, vf, dof, lse, delta)


def _dkv_pass(qf, kf, vf, dof, lse, delta, causal, scale, block_q,
              block_kv, interpret, out_dtype=None, window=None,
              q_offset=0, heads=None):
    """dK/dV for one (Tq, Tk) pair: kv blocks in the MIDDLE grid dim, q
    blocks INNERMOST so the accumulators carry across q steps.

    Under GQA (``heads=(h_q, h_kv)``) the INPUT k/v blocks come from the
    shared KV head while the OUTPUT stays per Q head — the caller
    group-sums the ``h_q // h_kv`` per-head partials (XLA fuses it)."""
    bh, tq, d = qf.shape
    tk = kf.shape[1]
    num_q, num_kv = tq // block_q, tk // block_kv
    khm = _kv_head_map(*heads) if heads else None
    if window is None:
        q_steps = num_q
        q_im = lambda bh, j, i: (bh, i, 0)
    else:
        q_steps = _q_window_steps(num_q, block_q, block_kv, window)
        q_im = _window_index_map(
            num_q,
            lambda j: _q_base(j, block_q, block_kv, window, q_offset),
        )
    q_spec_inner = pl.BlockSpec((1, block_q, d), q_im)
    kv_out_spec = pl.BlockSpec((1, block_kv, d), lambda bh, j, i: (bh, j, 0))
    if khm is None:
        kv_in_spec = kv_out_spec
    else:
        kv_in_spec = pl.BlockSpec(
            (1, block_kv, d), lambda bh, j, i: (khm(bh), j, 0)
        )
    row_spec_inner = pl.BlockSpec((1, block_q, 1), q_im)
    return pl.pallas_call(
        functools.partial(
            _dkv_kernel, scale=scale, causal=causal, block_q=block_q,
            block_kv=block_kv, num_q=q_steps, num_q_total=num_q,
            window=window, q_offset=q_offset,
        ),
        grid=(bh, num_kv, q_steps),
        in_specs=[q_spec_inner, kv_in_spec, kv_in_spec, q_spec_inner,
                  row_spec_inner, row_spec_inner],
        out_specs=[kv_out_spec, kv_out_spec],
        out_shape=[
            _sds((bh, tk, d), out_dtype or kf.dtype, qf),
            _sds((bh, tk, d), out_dtype or vf.dtype, qf),
        ],
        scratch_shapes=_scratch([(block_kv, d), (block_kv, d)]),
        interpret=interpret,
    )(qf, kf, vf, dof, lse, delta)


def _bwd(causal, scale, block_q, block_kv, interpret, window, res, g):
    qf, kf, vf, of, lse, qshape = res
    b, t, h, d = qshape
    h_kv = kf.shape[0] // b
    heads = (h, h_kv) if h_kv != h else None
    scale_v = _default_scale(scale, d)
    dof = _flat(g)
    # D_i = rowsum(dO * O): the softmax-jacobian correction term; rides
    # as (bh, t, 1) like lse (Mosaic trailing-block tiling rule)
    delta = (dof.astype(jnp.float32) * of.astype(jnp.float32)).sum(
        -1, keepdims=True
    )
    dq = _dq_pass(qf, kf, vf, dof, lse, delta, causal, scale_v, block_q,
                  block_kv, interpret, window=window, heads=heads)
    dk, dv = _dkv_pass(qf, kf, vf, dof, lse, delta, causal, scale_v,
                       block_q, block_kv, interpret, window=window,
                       heads=heads,
                       out_dtype=jnp.float32 if heads else None)
    if heads is not None:
        # GQA: the dkv pass emitted per-Q-HEAD partials (f32, so the
        # fold never sums rounded values); fold each group's onto its
        # shared KV head (fuses in XLA), then match the primal dtype
        tk = kf.shape[1]
        g_sz = h // h_kv

        def _fold(x, dt):
            return x.reshape(b, h_kv, g_sz, tk, d).sum(2).reshape(
                -1, tk, d
            ).astype(dt)

        return (_unflat(dq, b, h),
                _unflat(_fold(dk, kf.dtype), b, h_kv),
                _unflat(_fold(dv, vf.dtype), b, h_kv))
    return (_unflat(dq, b, h), _unflat(dk, b, h), _unflat(dv, b, h))


flash_attention.defvjp(_fwd, _bwd)


def make_flash_attention(causal=True, block_q=128, block_kv=128,
                         interpret=False, window=None):
    """``attn_fn`` closure for :func:`blendjax.models.seqformer.apply` —
    drop-in for the default ``full_attention``.

    ``block_q``/``block_kv`` may be ``'auto'``: the tile is then sized
    per call via :func:`flash_block_size`, so the closure works at any
    32-multiple sequence length (or any length up to 128, which fits a
    single tile) instead of requiring T to divide a fixed block.  Ragged
    lengths beyond that are rejected — the only "tile" dividing them is
    T itself, which would materialize the (T, T) score block the kernel
    exists to avoid (pad upstream instead).

    ``window=W`` enables sliding-window attention (causal only; see
    :func:`flash_attention`)."""
    _check_window(causal, window)

    def attn(q, k, v):
        t = q.shape[1]
        auto = flash_block_size(t)
        if (block_q == "auto" or block_kv == "auto") and auto == t and t > 128:
            raise ValueError(
                f"sequence length {t} has no flash tile (not a multiple "
                "of 32 and too long for a single tile); pad to a "
                "32-multiple upstream"
            )
        bq = auto if block_q == "auto" else block_q
        bkv = auto if block_kv == "auto" else block_kv
        return flash_attention(
            q, k, v, causal, None, bq, bkv, interpret, window
        )

    return attn
