"""Producer-side environment layer (reference ``btb/env.py:10-252``).

``BaseEnv`` is the gym.Env analog for Blender: because Blender's animation
system is the event loop, an env implements three hooks instead of one
``step``:

- ``_env_reset()``              — restore initial state (pre_animation)
- ``_env_prepare_step(action)`` — apply the action *before* the frame so
  physics integrates it (pre_frame; rationale reference ``env.py:144-159``)
- ``_env_post_step()``          — gather ``{obs, reward, done, ...}`` after
  the frame completed (post_frame)

``RemoteControlledAgent`` bridges this callback world to a blocking
remote ``step()/reset()`` peer (:class:`blendjax.btt.env.RemoteEnv`) via a
REP socket: one remote ``step()`` == one simulated frame.  With
``real_time=True`` the socket goes non-blocking and simulation time
advances even when the agent is slow (missed frames step with no action).

Requests stamped with a correlation id (``wire.BTMID_KEY`` — the
pipelined :class:`blendjax.btt.envpool.EnvPool` and any fault-policy
retry path do this) get the id echoed in the reply, and a re-sent
request carrying the id of a step already served is answered from the
``wire.REPLY_CACHE_DEPTH``-deep reply cache instead of simulating the
frame twice — the
consumer-side retry of a non-idempotent ``step`` becomes exactly-once
(see the caveat in :mod:`blendjax.btt.faults`).  Unstamped requests
(reference consumers) behave exactly as before.

Requests additionally carrying a span context (``wire.SPAN_KEY`` — a
tracing ``EnvPool``) get a producer-side trace span back, piggybacked
on the reply under ``wire.SPANS_KEY``: one ``producer_step`` span
covering request-receipt through reply-send — i.e. the frame's physics
+ render inside Blender's animation loop — tagged with the request's
correlation id, so the consumer's merged Perfetto timeline shows the
producer's share of every env step in its own process row (see
:mod:`blendjax.obs.spans` and docs/observability.md).  Span-less
requests pay nothing.

Module import needs no bpy; only instantiating ``BaseEnv`` touches the
animation system, so the RPC state machine is unit-testable in CI.
"""

from __future__ import annotations

from collections import OrderedDict

import zmq

from blendjax import wire
from blendjax.btb.constants import DEFAULT_TIMEOUTMS


class BaseEnv:
    """Abstract Blender environment driven by animation callbacks.

    Params
    ------
    agent: callable
        ``cmd, action = agent(env, **ctx)`` invoked each frame (from the
        second frame of an episode on); ``ctx`` holds at least
        ``obs/reward/done/prev_action/time``.
    """

    STATE_INIT = "init"
    STATE_RUN = "run"
    CMD_RESTART = "restart"
    CMD_STEP = "step"

    def __init__(self, agent):
        from blendjax.btb.animation import AnimationController

        self.events = AnimationController()
        self.events.pre_animation.add(self._pre_animation)
        self.events.pre_frame.add(self._pre_frame)
        self.events.post_frame.add(self._post_frame)
        self.agent = agent
        self.ctx = None
        self.renderer = None
        self.render_every = None
        self.frame_range = None
        self.state = BaseEnv.STATE_INIT

    def run(self, frame_range=None, use_animation=True):
        """Enter the env loop.  The playback range end is pinned far past
        the scene range so episodes may outlive it (reference ``env.py:74``);
        ``frame_range`` only determines the ``done`` horizon."""
        from blendjax.btb.animation import AnimationController

        self.frame_range = AnimationController.setup_frame_range(frame_range)
        self.events.play(
            (self.frame_range[0], 2147483647),
            num_episodes=-1,
            use_animation=use_animation,
            use_offline_render=True,
        )

    def attach_default_renderer(self, every_nth=1):
        """Render every nth frame into ``ctx['rgb_array']`` for remote
        ``env.render()`` (reference ``env.py:79-95``)."""
        from blendjax.btb.camera import Camera
        from blendjax.btb.offscreen import OffScreenRenderer

        self.renderer = OffScreenRenderer(camera=Camera(), mode="rgb", gamma=True)
        self.render_every = every_nth

    def attach_param_channel(self, channel, apply=None):
        """Receive mid-training scene-parameter pushes — the densityopt
        receiver (reference ``examples/densityopt``) as a first-class
        hook, and the producer half of the scenario plane's live domain
        randomization (docs/scenarios.md).

        ``channel`` is a producer-side (bound)
        :class:`blendjax.btb.duplex.DuplexChannel`; every frame, queued
        messages are drained non-blocking (``recv(timeoutms=0)``) and
        each is handed to ``apply`` (or the :meth:`_env_apply_params`
        hook), so a push lands within one frame of arriving and a
        silent channel costs one poll per frame.  Messages apply BEFORE
        the next action is integrated (the poll runs ahead of the
        agent callback in the frame), so a pushed physics rate or scene
        param takes effect on the very next simulated step."""
        self.param_channel = channel
        self._param_apply = apply
        # ahead of the agent callback registered in __init__: params
        # must apply before the frame's action is prepared
        self.events.pre_frame.add_first(self._poll_params)

    def _poll_params(self):
        chan = getattr(self, "param_channel", None)
        if chan is None:
            return
        while True:
            msg = chan.recv(timeoutms=0)
            if msg is None:
                break
            fn = getattr(self, "_param_apply", None)
            (fn or self._env_apply_params)(msg)

    # -- animation callbacks ------------------------------------------------

    def _pre_animation(self):
        self.state = BaseEnv.STATE_INIT
        self.ctx = {"prev_action": None, "done": False}
        self._env_reset()

    def _pre_frame(self):
        self.ctx["time"] = self.events.frameid
        self.ctx["done"] |= self.events.frameid >= self.frame_range[1]
        if self.events.frameid > self.frame_range[0]:
            cmd, action = self.agent(self, **self.ctx)
            if cmd == BaseEnv.CMD_RESTART:
                self._restart()
            elif cmd == BaseEnv.CMD_STEP:
                if action is not None:
                    self._env_prepare_step(action)
                    self.ctx["prev_action"] = action
                self.state = BaseEnv.STATE_RUN

    def _post_frame(self):
        self._render(self.ctx)
        self.ctx = {**self.ctx, **self._env_post_step()}

    def _render(self, ctx):
        if self.renderer is not None:
            offset = self.events.frameid - self.frame_range[0]
            if offset % self.render_every == 0:
                ctx["rgb_array"] = self.renderer.render()

    def _restart(self):
        self.events.rewind()

    # -- to be implemented by concrete envs ---------------------------------

    def _env_reset(self):
        """Reset state to initial; returns nothing."""
        raise NotImplementedError

    def _env_prepare_step(self, action):
        """Apply ``action`` before the frame simulates."""
        raise NotImplementedError

    def _env_post_step(self):
        """Return ``{obs, reward, ...}`` (and optionally ``done``) after the
        frame completed."""
        raise NotImplementedError

    def _env_apply_params(self, msg):
        """Apply one mid-training parameter push received over the
        attached duplex channel (:meth:`attach_param_channel`) — a
        message dict, typically ``{"cmd": "scenario", "scenario":
        name, "params": {...}}`` from a
        :class:`~blendjax.scenario.DomainRandomizer`.  Default: no-op,
        so envs that never randomize pay nothing for the hook; a
        scenario-aware env overrides it, applies what it understands,
        and echoes the applied scenario name in its post-step dict so
        the consumer can attribute transitions (docs/scenarios.md)."""


class RemoteControlledAgent:
    """REP-socket agent: requests from a remote peer drive the env.

    State machine per frame callback (reference ``env.py:206-252``):
    in REP state, send the previous frame's ctx (the reply to the last
    RPC); then in REQ state, receive ``{cmd: 'reset'|'step', action}`` and
    translate to ``CMD_RESTART``/``CMD_STEP``.  A ``reset`` arriving while
    the env is already freshly reset recurses to serve the follow-up
    request immediately (so remote ``reset()`` returns the initial obs
    without consuming a frame).

    Params
    ------
    address: str
        Endpoint to bind (from ``-btsockets GYM=...``).
    real_time: bool
        Non-blocking mode: simulation never waits; missed exchanges step
        with ``action=None``.
    timeoutms: int
        Socket send/recv timeout.
    """

    STATE_REQ = "await_request"
    STATE_REP = "send_reply"

    #: replies kept for duplicate suppression — must cover the consumer's
    #: whole in-flight window (its pipeline depth), since a retry can
    #: target any outstanding request, not just the newest.  Shared with
    #: the consumer via ``wire`` so ``EnvPool`` can refuse a
    #: ``pipeline_depth`` that outruns the window.  Kept small: cached
    #: replies hold full payloads (rgb_array included), so this bounds
    #: producer-side memory at depth * frame size
    REPLY_CACHE_DEPTH = wire.REPLY_CACHE_DEPTH

    def __init__(self, address, real_time=False, timeoutms=DEFAULT_TIMEOUTMS):
        self._ctx = zmq.Context.instance()
        self.socket = self._ctx.socket(zmq.REP)
        self.socket.setsockopt(zmq.LINGER, 0)
        self.socket.setsockopt(zmq.SNDTIMEO, timeoutms)
        self.socket.setsockopt(zmq.RCVTIMEO, timeoutms)
        self.socket.bind(address)
        self.real_time = real_time
        self.state = RemoteControlledAgent.STATE_REQ
        # correlation-id bookkeeping: _pending_mid rides the request being
        # simulated; once its reply goes out it joins _reply_cache
        # (mid -> reply) for duplicate suppression.  A pipelined consumer
        # (EnvPool pipeline_depth > 1) may retry ANY of its in-flight
        # requests — its oldest expired first — so the cache must cover
        # the whole window, not just the newest reply; REPLY_CACHE_DEPTH
        # comfortably exceeds any sane pipeline depth.
        self._pending_mid = None
        self._reply_cache = OrderedDict()
        self._dup_reply = None  # cached reply owed after a NOBLOCK Again
        # span context of the request being simulated: (trace id,
        # receipt time in epoch us); rides into the reply as a
        # producer-side span when the request asked for one
        self._pending_span = None

    def __call__(self, env, **ctx):
        flags = 0
        if self.real_time and env.state == BaseEnv.STATE_RUN:
            flags = zmq.NOBLOCK

        if self._dup_reply is not None:
            # a duplicate request consumed last frame is still owed its
            # cached reply (REP alternation): flush before anything else
            try:
                wire.send_message(self.socket, self._dup_reply, flags=flags)
                self._dup_reply = None
            except zmq.Again:
                if not self.real_time:
                    raise TimeoutError(
                        "Failed to re-send cached reply to remote agent."
                    ) from None
                return BaseEnv.CMD_STEP, None

        if self.state == RemoteControlledAgent.STATE_REP:
            reply = ctx
            if self._pending_mid is not None:
                reply = {**ctx, wire.BTMID_KEY: self._pending_mid}
            if self._pending_span is not None:
                from blendjax.obs.spans import make_span

                trace, t0_us = self._pending_span
                reply = dict(reply)
                reply[wire.SPANS_KEY] = [make_span(
                    "producer_step", t0_us, trace=trace, cat="producer",
                )]
            try:
                wire.send_message(self.socket, reply, flags=flags)
                self.state = RemoteControlledAgent.STATE_REQ
                if self._pending_mid is not None:
                    self._reply_cache[self._pending_mid] = reply
                    while len(self._reply_cache) > self.REPLY_CACHE_DEPTH:
                        self._reply_cache.popitem(last=False)
                    self._pending_mid = None
                self._pending_span = None
            except zmq.Again:
                if not self.real_time:
                    raise TimeoutError("Failed to send reply to remote agent.")
                return BaseEnv.CMD_STEP, None

        while True:
            try:
                request = self.socket.recv(flags=flags)
            except zmq.Again:
                return BaseEnv.CMD_STEP, None
            request = wire.loads(request)
            mid = request.get(wire.BTMID_KEY)
            if mid is not None and mid in self._reply_cache:
                # consumer retry of a step already simulated: serve the
                # cached reply (exactly-once) and await the real next
                # request.  The send is safe mid-cycle — REP queues to
                # (or discards for) the requesting peer; under real_time
                # a full pipe stashes the owed reply for the next frame
                # instead of raising inside Blender's frame callback.
                try:
                    wire.send_message(
                        self.socket, self._reply_cache[mid], flags=flags
                    )
                except zmq.Again:
                    if not self.real_time:
                        raise TimeoutError(
                            "Failed to re-send cached reply to remote agent."
                        ) from None
                    self._dup_reply = self._reply_cache[mid]
                    return BaseEnv.CMD_STEP, None
                continue
            break
        cmd_name = request.get("cmd")
        if cmd_name not in ("reset", "step"):
            raise ValueError(f"unknown remote command {cmd_name!r}")
        self.state = RemoteControlledAgent.STATE_REP
        self._pending_mid = mid
        span_ctx = request.get(wire.SPAN_KEY)
        if isinstance(span_ctx, dict) and span_ctx.get("trace") is not None:
            from blendjax.obs.spans import now_us

            self._pending_span = (span_ctx["trace"], now_us())
        else:
            self._pending_span = None

        if cmd_name == "reset":
            if env.state == BaseEnv.STATE_INIT:
                # Already reset: reply with the fresh ctx and serve the
                # follow-up request in the same frame.
                return self.__call__(env, **ctx)
            return BaseEnv.CMD_RESTART, None
        return BaseEnv.CMD_STEP, request.get("action")

    def close(self):
        self.socket.close(0)
