"""Animation-driven producer event loop (reference ``btb/animation.py:9-212``).

The defining architectural idea carried over from the reference: **Blender's
animation system is the event loop**.  Producer work happens inside
callbacks Blender invokes around each frame; nothing here spins its own
loop except the blocking fallback for ``--background`` mode.

Signals (invoked in this order over a play of E episodes x F frames)::

    pre_play
      [ pre_animation  (pre_frame post_frame) x F  post_animation ] x E
    post_play

Two modes:

- ``use_animation=True`` (UI): non-blocking.  Hooks
  ``frame_change_pre``; ``post_frame`` fires either from a ``POST_PIXEL``
  draw handler (GL context valid there — required for offscreen rendering)
  or from ``frame_change_post``.  Playback advances via
  ``bpy.ops.screen.animation_play``.
- ``use_animation=False`` (``--background``): a blocking loop stepping
  ``frame_set``, which synchronously fires the same handlers.

``POST_PIXEL`` may fire several times per frame; a pending/last-frame guard
dedupes (reference ``animation.py:51-65,182-191``).
"""

from __future__ import annotations

import sys

import bpy

from blendjax.btb.signal import Signal


class _Playback:
    """Per-play bookkeeping."""

    def __init__(self, frame_range, num_episodes, use_animation, use_offline_render):
        self.frame_range = frame_range
        self.num_episodes = num_episodes
        self.use_animation = use_animation
        self.use_offline_render = use_offline_render
        self.episode = 0
        self.pending_post_frame = False
        self.last_post_frame = None
        self.draw_handler = None
        self.draw_space = None


class AnimationController:
    """Fine-grained callbacks around Blender's animation playback."""

    def __init__(self):
        self.pre_play = Signal()
        self.pre_animation = Signal()
        self.pre_frame = Signal()
        self.post_frame = Signal()
        self.post_animation = Signal()
        self.post_play = Signal()
        self._pb = None

    @property
    def frameid(self):
        """Current scene frame."""
        return bpy.context.scene.frame_current

    @property
    def playing(self):
        return self._pb is not None

    @staticmethod
    def setup_frame_range(frame_range=None, physics=True):
        """Apply (start, end) inclusive to the scene and, when ``physics``,
        to the rigid-body point cache so simulation covers the animation
        range (reference ``animation.py:108-134``)."""
        scene = bpy.context.scene
        if frame_range is None:
            frame_range = (scene.frame_start, scene.frame_end)
        scene.frame_start, scene.frame_end = frame_range
        if physics and getattr(scene, "rigidbody_world", None):
            cache = scene.rigidbody_world.point_cache
            cache.frame_start, cache.frame_end = frame_range
        return frame_range

    def play(
        self,
        frame_range=None,
        num_episodes=-1,
        use_animation=True,
        use_offline_render=True,
        use_physics=True,
    ):
        """Start playback.

        Params
        ------
        frame_range: (start, end) inclusive | None
            Defaults to the scene's range.
        num_episodes: int
            Loops to play; -1 plays forever.
        use_animation: bool
            True: non-blocking via Blender's player (UI responsive, target
            FPS).  False: blocking loop, as fast as possible (background).
        use_offline_render: bool
            Route ``post_frame`` through a POST_PIXEL draw handler so
            offscreen rendering is safe inside it.
        use_physics: bool
            Sync the rigid-body cache to the frame range.
        """
        if self._pb is not None:
            raise RuntimeError("Animation already running")
        self._pb = _Playback(
            frame_range=AnimationController.setup_frame_range(
                frame_range, physics=use_physics
            ),
            num_episodes=num_episodes if num_episodes >= 0 else sys.maxsize,
            use_animation=use_animation,
            use_offline_render=use_offline_render,
        )
        self.pre_play.invoke()
        if use_animation:
            self._start_nonblocking()
        else:
            self._run_blocking()

    def _start_nonblocking(self):
        bpy.app.handlers.frame_change_pre.append(self._handle_pre_frame)
        if self._pb.use_offline_render:
            from blendjax.btb.utils import find_first_view3d

            _, self._pb.draw_space, _ = find_first_view3d()
            self._pb.draw_handler = bpy.types.SpaceView3D.draw_handler_add(
                self._handle_post_frame, (), "WINDOW", "POST_PIXEL"
            )
        else:
            bpy.app.handlers.frame_change_post.append(self._handle_post_frame)
        bpy.context.scene.frame_set(self._pb.frame_range[0])
        bpy.ops.screen.animation_play()

    def _run_blocking(self):
        bpy.app.handlers.frame_change_pre.append(self._handle_pre_frame)
        bpy.app.handlers.frame_change_post.append(self._handle_post_frame)
        start, end = self._pb.frame_range
        while self._pb is not None and self._pb.episode < self._pb.num_episodes:
            bpy.context.scene.frame_set(start)
            while self._pb is not None and self.frameid < end:
                bpy.context.scene.frame_set(self.frameid + 1)
            # _handle_post_frame may have called stop() -> _pb is None

    def rewind(self):
        """Jump back to the first frame of the range."""
        if self._pb is not None:
            bpy.context.scene.frame_set(self._pb.frame_range[0])

    def stop(self):
        """Stop playback, unregister handlers, fire ``post_play``.

        Public in blendjax (the reference only cancels internally on
        episode exhaustion, ``animation.py:201-212``).
        """
        if self._pb is None:
            return
        pb = self._pb
        bpy.app.handlers.frame_change_pre.remove(self._handle_pre_frame)
        if pb.draw_handler is not None:
            bpy.types.SpaceView3D.draw_handler_remove(pb.draw_handler, "WINDOW")
            pb.draw_handler = None
        else:
            bpy.app.handlers.frame_change_post.remove(self._handle_post_frame)
        if pb.use_animation:
            bpy.ops.screen.animation_cancel(restore_frame=False)
        self._pb = None
        self.post_play.invoke()

    # -- frame callbacks ----------------------------------------------------

    def _handle_pre_frame(self, scene, *args):
        if self._pb is None:
            return
        if self.frameid == self._pb.frame_range[0]:
            self.pre_animation.invoke()
        self.pre_frame.invoke()
        self._pb.pending_post_frame = True

    def _skip_post_frame(self):
        """POST_PIXEL dedupe: only the first draw after a pre_frame, once
        per frame, and only for the hooked space."""
        pb = self._pb
        return (
            not pb.pending_post_frame
            or pb.last_post_frame == self.frameid
            or (
                pb.use_animation
                and pb.use_offline_render
                and bpy.context.space_data != pb.draw_space
            )
        )

    def _handle_post_frame(self, *args):
        if self._pb is None or self._skip_post_frame():
            return
        self._pb.pending_post_frame = False
        self._pb.last_post_frame = self.frameid

        self.post_frame.invoke()
        if self.frameid == self._pb.frame_range[1]:
            self.post_animation.invoke()
            self._pb.episode += 1
            if self._pb.episode >= self._pb.num_episodes:
                self.stop()
