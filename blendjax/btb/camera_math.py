"""Pure-numpy camera projection math.

The reference fuses this math into its bpy ``Camera`` wrapper
(``btb/camera.py:84-136``) and ``btb/utils.py:112-121``, making it
untestable without Blender.  blendjax splits the math out: these functions
have no bpy dependency, run under golden-value tests in CI, and are equally
usable from the consumer side (e.g. re-projecting keypoints in a JAX
training loop — they are jax.numpy-compatible since they only use
``concatenate``/``matmul``/slicing).

Conventions match Blender/OpenGL: camera looks down -Z, NDC in [-1, 1]^3,
view = inverse of the camera's world matrix.
"""

from __future__ import annotations

import numpy as np


def hom(x, v=1.0):
    """Append a homogeneous coordinate ``v`` along the last axis
    (reference ``utils.py:112-117``)."""
    x = np.atleast_2d(x)
    pad = np.full((*x.shape[:-1], 1), v, dtype=x.dtype)
    return np.concatenate((x, pad), axis=-1)


def dehom(x):
    """Perspective division by the last coordinate (reference
    ``utils.py:119-121``)."""
    return x[..., :-1] / x[..., -1:]


def world_to_ndc(xyz_world, view_matrix, proj_matrix, return_depth=False):
    """Project Nx3 world points to normalized device coordinates.

    With ``return_depth`` also returns linear depth along the camera's
    viewing direction (positive in front of the camera) — the annotation
    signal used for keypoint depth labels (reference ``camera.py:84-112``).
    """
    view = np.asarray(view_matrix, dtype=np.float64)
    proj = np.asarray(proj_matrix, dtype=np.float64)
    xyzw = hom(np.atleast_2d(np.asarray(xyz_world, dtype=np.float64)))
    cam = xyzw @ view.T
    ndc = dehom(cam @ proj.T)
    if return_depth:
        return ndc, -cam[:, 2].copy()  # camera looks down -Z
    return ndc


def ndc_to_pixel(ndc, shape, origin="upper-left"):
    """Map NDC xy to pixel coordinates for an (H, W) image.

    ``origin='upper-left'`` yields OpenCV convention, ``'lower-left'``
    OpenGL (reference ``camera.py:115-136``).
    """
    if origin not in ("upper-left", "lower-left"):
        raise ValueError(f"unknown origin {origin!r}")
    h, w = shape
    xy = (np.atleast_2d(ndc)[:, :2] + 1.0) * 0.5
    if origin == "upper-left":
        xy = np.stack([xy[:, 0], 1.0 - xy[:, 1]], axis=-1)
    return xy * np.array([[w, h]], dtype=xy.dtype)


def project_points(
    xyz_world, view_matrix, proj_matrix, shape, origin="upper-left", return_depth=False
):
    """world -> pixel composition (reference ``camera.py:138-162``)."""
    if return_depth:
        ndc, z = world_to_ndc(xyz_world, view_matrix, proj_matrix, return_depth=True)
        return ndc_to_pixel(ndc, shape, origin), z
    return ndc_to_pixel(
        world_to_ndc(xyz_world, view_matrix, proj_matrix), shape, origin
    )


def look_at_matrix(eye, target, up=(0.0, 0.0, 1.0)):
    """4x4 view matrix for a camera at ``eye`` looking at ``target``.

    Equivalent to Blender's ``to_track_quat('-Z', 'Y')`` placement followed
    by world-matrix inversion (reference ``camera.py:191-204``): the camera
    -Z axis points at the target, +Y is the projected up vector.
    """
    eye = np.asarray(eye, dtype=np.float64)
    target = np.asarray(target, dtype=np.float64)
    fwd = target - eye
    fwd = fwd / np.linalg.norm(fwd)
    upv = np.asarray(up, dtype=np.float64)
    right = np.cross(fwd, upv)
    norm = np.linalg.norm(right)
    if norm < 1e-9:  # looking along up: pick an arbitrary right vector
        right = np.cross(fwd, np.array([1.0, 0.0, 0.0]))
        norm = np.linalg.norm(right)
    right = right / norm
    true_up = np.cross(right, fwd)

    view = np.eye(4)
    view[0, :3] = right
    view[1, :3] = true_up
    view[2, :3] = -fwd
    view[:3, 3] = -view[:3, :3] @ eye
    return view


def perspective_projection(fov_y, aspect, near, far):
    """Symmetric OpenGL-style perspective matrix.

    ``fov_y`` is the full vertical field of view in radians; ``aspect`` is
    width/height.  Matches what Blender's ``calc_matrix_camera`` produces
    for a perspective camera with equivalent sensor/lens settings.
    """
    f = 1.0 / np.tan(fov_y / 2.0)
    proj = np.zeros((4, 4))
    proj[0, 0] = f / aspect
    proj[1, 1] = f
    proj[2, 2] = -(far + near) / (far - near)
    proj[2, 3] = -(2.0 * far * near) / (far - near)
    proj[3, 2] = -1.0
    return proj


def orthographic_projection(scale, aspect, near, far):
    """OpenGL-style orthographic matrix.

    ``scale`` is the full width of the view volume (Blender's
    ``ortho_scale``); height follows from ``aspect`` = width/height.
    """
    half_w = scale / 2.0
    half_h = half_w / aspect
    proj = np.eye(4)
    proj[0, 0] = 1.0 / half_w
    proj[1, 1] = 1.0 / half_h
    proj[2, 2] = -2.0 / (far - near)
    proj[2, 3] = -(far + near) / (far - near)
    return proj


def bbox_corners(minimum, maximum):
    """8 corner points of an axis-aligned box, Nx3."""
    mn = np.asarray(minimum, dtype=np.float64)
    mx = np.asarray(maximum, dtype=np.float64)
    corners = []
    for x in (mn[0], mx[0]):
        for y in (mn[1], mx[1]):
            for z in (mn[2], mx[2]):
                corners.append((x, y, z))
    return np.array(corners)


def random_spherical_loc(radius_range=None, theta_range=None, phi_range=None, rng=None):
    """Random location on a sphere shell — the domain-randomization helper
    (reference ``utils.py:123-156``).  ``rng`` is a ``numpy.random.Generator``
    for reproducibility (the reference uses the global seed only)."""
    rng = rng or np.random.default_rng()
    r_lo, r_hi = radius_range or (1.0, 1.0)
    t_lo, t_hi = theta_range or (0.0, np.pi)
    p_lo, p_hi = phi_range or (0.0, 2 * np.pi)
    r = rng.uniform(r_lo, r_hi)
    t = rng.uniform(t_lo, t_hi)
    p = rng.uniform(p_lo, p_hi)
    return np.array(
        [np.sin(t) * np.cos(p), np.sin(t) * np.sin(p), np.cos(t)]
    ) * r
