"""Launcher -> Blender-script argument protocol.

The launcher passes framework args after Blender's ``--`` separator:
``-btid <int> -btseed <int> -btsockets NAME=ADDR [NAME=ADDR ...]`` plus any
user-supplied per-instance args (reference
``pkg_blender/blendtorch/btb/arguments.py:5-47``,
``pkg_pytorch/blendtorch/btt/launcher.py:114-122``).  This module parses that
protocol inside the Blender process; user scripts argparse the remainder.
"""

from __future__ import annotations

import argparse
import sys
from dataclasses import dataclass, field


@dataclass
class BlendJaxArgs:
    """Parsed framework arguments for one producer instance."""

    btid: int = 0
    btseed: int = 0
    btsockets: dict = field(default_factory=dict)


def _parse_socket_list(pairs):
    sockets = {}
    for item in pairs:
        name, sep, addr = item.partition("=")
        if not sep or not name or not addr:
            raise ValueError(
                f"invalid -btsockets entry {item!r}; expected NAME=ADDRESS"
            )
        sockets[name] = addr
    return sockets


def parse_blendtorch_args(argv=None):
    """Parse framework args after Blender's ``--`` separator.

    Returns ``(BlendJaxArgs, remainder)`` where ``remainder`` holds any
    unrecognized args for the user script's own argparse (the reference
    returns the same pair, ``arguments.py:38-46``; usage e.g.
    ``tests/blender/env.blend.py:32-37``).

    ``argv`` defaults to ``sys.argv``; only tokens after the first ``--`` are
    considered, mirroring Blender's convention of ignoring script args.
    """
    argv = list(sys.argv) if argv is None else list(argv)
    if "--" in argv:
        argv = argv[argv.index("--") + 1:]

    parser = argparse.ArgumentParser(prog="blendjax", add_help=False)
    parser.add_argument("-btid", type=int, default=0, help="producer instance id")
    parser.add_argument("-btseed", type=int, default=0, help="per-instance RNG seed")
    parser.add_argument(
        "-btsockets",
        nargs="*",
        default=[],
        metavar="NAME=ADDR",
        help="named socket addresses",
    )
    known, remainder = parser.parse_known_args(argv)

    args = BlendJaxArgs(
        btid=known.btid,
        btseed=known.btseed,
        btsockets=_parse_socket_list(known.btsockets),
    )
    return args, remainder


# blendjax-native alias
parse_btargs = parse_blendtorch_args
