"""Data-plane publisher running inside Blender (reference ``btb/publisher.py:4-43``).

A PUSH socket that **binds** (consumers connect to all producers, giving M:N
fan-in with ZMQ fair queuing).  ``SNDHWM`` is small and ``IMMEDIATE=1`` so a
producer stalls when the trainer lags instead of buffering frames
unboundedly — the backpressure that keeps memory flat when Blender renders
faster than the TPU consumes (reference ``publisher.py:21-27``,
``examples/datagen/Readme.md:168-175``).

Unlike the reference this module needs no ``bpy``: it is plain ZMQ and is
exercised directly by the fake-Blender test fleet.  Set ``raw_buffers=True``
to use blendjax's zero-copy multipart encoding for ndarray payloads (see
:mod:`blendjax.wire`); leave it False for byte-compat with reference
consumers.
"""

from __future__ import annotations

import zmq

from blendjax import wire


class DataPublisher:
    """Publishes message dicts to connected consumers.

    Params
    ------
    bind_address: str
        Address to bind, e.g. ``tcp://127.0.0.1:11000`` (from
        ``-btsockets DATA=...``).
    btid: int | None
        Producer id stamped into every message.
    send_hwm: int
        High-water mark; send blocks once this many messages queue.
    raw_buffers: bool
        Use zero-copy multipart encoding for ndarrays.
    """

    def __init__(
        self,
        bind_address,
        btid=None,
        send_hwm=wire.DEFAULT_HWM,
        raw_buffers=False,
        lingerms=0,
        sndtimeoms=None,
        shm_capacity=64 << 20,
    ):
        self.btid = btid
        self.raw_buffers = raw_buffers
        self._sndtimeoms = -1 if sndtimeoms is None else sndtimeoms
        self.sock = None
        self._ring = None
        if bind_address.startswith("shm://"):
            # same-host native transport: single memcpy into a shared-memory
            # ring, no tcp/kernel copies (see blendjax/native/ringbuf.cpp)
            from blendjax.native import ShmRingWriter

            self._ring = ShmRingWriter(bind_address, capacity_bytes=shm_capacity)
            return
        self._ctx = zmq.Context.instance()
        self.sock = self._ctx.socket(zmq.PUSH)
        self.sock.setsockopt(zmq.SNDHWM, send_hwm)
        self.sock.setsockopt(zmq.IMMEDIATE, 1)
        self.sock.setsockopt(zmq.LINGER, lingerms)
        if sndtimeoms is not None:
            self.sock.setsockopt(zmq.SNDTIMEO, sndtimeoms)
        self.sock.bind(bind_address)

    def publish(self, **kwargs):
        """Send one message dict; blocks under backpressure.

        ``btid`` is stamped automatically (reference ``publisher.py:41-43``).
        With ``sndtimeoms`` set, returns False instead of blocking past the
        timeout (lets an animation loop keep simulating while stalled —
        blendjax extension, the reference blocks indefinitely).
        """
        data = {wire.BTID_KEY: self.btid, **kwargs}
        if self._ring is not None:
            frames = wire.encode(data, raw_buffers=self.raw_buffers)
            return self._ring.send_frames(frames, timeout_ms=self._sndtimeoms)
        try:
            wire.send_message(self.sock, data, raw_buffers=self.raw_buffers)
        except zmq.Again:
            return False
        return True

    def close(self):
        if self._ring is not None:
            self._ring.close(unlink=False)  # reader may still drain
            self._ring = None
        if self.sock is not None:
            self.sock.close(0)
            self.sock = None
