"""Producer-side duplex channel (reference ``btb/duplex.py``): binds the
PAIR socket inside Blender; the consumer connects."""

from __future__ import annotations

from blendjax._duplex import DuplexChannelBase
from blendjax.btb.constants import DEFAULT_TIMEOUTMS


class DuplexChannel(DuplexChannelBase):
    DEFAULT_TIMEOUTMS = DEFAULT_TIMEOUTMS

    def __init__(self, address, btid=None, lingerms=0, timeoutms=None, raw_buffers=False):
        super().__init__(
            address,
            btid=btid,
            bind=True,
            lingerms=lingerms,
            timeoutms=timeoutms,
            raw_buffers=raw_buffers,
        )
