"""Eevee offscreen renderer (reference ``btb/offscreen.py:9-112``).

Renders the first 3D viewport into a ``gpu.types.GPUOffScreen`` and reads
the color texture back as a numpy uint8 HxWxC array.  Must be called from a
context where the GL context is valid — i.e. inside the POST_PIXEL draw
callback that ``AnimationController(use_offline_render=True)`` provides.

Readback strategy, newest first:

1. ``GPUTexture.read()`` (Blender >= 3.0) — returns a ``gpu.types.Buffer``
   that supports the Python buffer protocol: zero-copy ``np.asarray``.
2. PyOpenGL ``glGetTexImage`` — the reference's workaround for Blender 2.8x
   where ``bgl.Buffer`` lacked the buffer protocol
   (reference ``offscreen.py:85-92``).

Gamma correction: Blender renders linear color.  The reference optionally
applies ``pow(c, 1/2.2)`` per pixel in numpy on the producer CPU
(``offscreen.py:105-112``); blendjax defaults to shipping linear frames and
doing sRGB encode on the TPU via :func:`blendjax.ops.image.linear_to_srgb`,
where it fuses into the input pipeline for free.  Set ``gamma=True`` for
reference-compatible producer-side correction.
"""

from __future__ import annotations

import numpy as np

try:
    import bpy
    import gpu
    from gpu_extras.presets import draw_texture_2d  # noqa: F401 (kept for users)
except ImportError:  # pragma: no cover - outside Blender
    bpy = None
    gpu = None


class OffScreenRenderer:
    """Offscreen Eevee render of the first 3D viewport.

    Params
    ------
    camera: blendjax.btb.Camera | None
        Camera providing view/projection matrices; defaults to scene camera.
    mode: 'rgb' | 'rgba'
        Channels of the returned array.
    origin: 'upper-left' | 'lower-left'
        Row order of the returned image.
    gamma: bool
        Apply producer-side gamma correction (see module docstring).
    """

    def __init__(self, camera=None, mode="rgb", origin="upper-left", gamma=False):
        from blendjax.btb.camera import Camera
        from blendjax.btb.utils import find_first_view3d

        if mode not in ("rgb", "rgba"):
            raise ValueError(f"unknown mode {mode!r}")
        self.camera = camera or Camera()
        self.mode = mode
        self.origin = origin
        self.gamma = gamma
        h, w = self.camera.shape
        self.offscreen = gpu.types.GPUOffScreen(w, h)
        self.area, self.space, self.region = find_first_view3d()
        self.shading = None  # set via set_render_style

    def set_render_style(self, shading="RENDERED", overlays=False):
        """Viewport shading for subsequent renders (reference
        ``offscreen.py:101-103``)."""
        self.space.shading.type = shading
        self.space.overlay.show_overlays = overlays

    def render(self):
        """Render and return HxWx{3,4} uint8 (reference ``offscreen.py:68-99``)."""
        h, w = self.camera.shape
        self.offscreen.draw_view3d(
            bpy.context.scene,
            bpy.context.view_layer,
            self.space,
            self.region,
            _as_matrix(self.camera.view_matrix),
            _as_matrix(self.camera.proj_matrix),
            do_color_management=self.gamma,
        )
        rgba = self._read_texture(w, h)
        img = rgba[..., :3] if self.mode == "rgb" else rgba
        if self.origin == "upper-left":
            img = np.flipud(img)
        return np.ascontiguousarray(img)

    def _read_texture(self, w, h):
        tex = getattr(self.offscreen, "texture_color", None)
        if tex is not None and hasattr(tex, "read"):
            buf = tex.read()  # gpu.types.Buffer, float32 RGBA in Blender 3.x+
            arr = np.asarray(buf, dtype=np.float32).reshape(h, w, 4)
            return (np.clip(arr, 0.0, 1.0) * 255).astype(np.uint8)
        return self._read_texture_gl(w, h)

    def _read_texture_gl(self, w, h):  # pragma: no cover - legacy Blender
        """PyOpenGL fallback for Blender 2.8x (reference ``offscreen.py:85-92``)."""
        import bgl
        from OpenGL.GL import GL_RGBA, GL_TEXTURE_2D, GL_UNSIGNED_BYTE, glGetTexImage

        buffer = np.zeros((h, w, 4), dtype=np.uint8)
        bgl.glActiveTexture(bgl.GL_TEXTURE0)
        bgl.glBindTexture(bgl.GL_TEXTURE_2D, self.offscreen.color_texture)
        glGetTexImage(GL_TEXTURE_2D, 0, GL_RGBA, GL_UNSIGNED_BYTE, buffer)
        return buffer

    def free(self):
        self.offscreen.free()


def _as_matrix(m):
    """numpy 4x4 -> mathutils.Matrix for the gpu API."""
    from mathutils import Matrix

    return Matrix([list(row) for row in np.asarray(m)])
