"""Blender-side scene utilities (reference ``btb/utils.py:6-192``).

Pure math (hom/dehom/random_spherical_loc) lives in
:mod:`blendjax.btb.camera_math` and is re-exported here for API parity;
everything below needs ``bpy`` and runs only inside Blender.
"""

from __future__ import annotations

import numpy as np

from blendjax.btb.camera_math import (  # noqa: F401  (re-exports, parity)
    dehom,
    hom,
    random_spherical_loc,
)

try:
    import bpy
except ImportError:  # pragma: no cover - outside Blender
    bpy = None
try:
    from mathutils import Vector
except ImportError:  # pragma: no cover - outside Blender
    Vector = None


def find_first_view3d():
    """First VIEW_3D area, its space, and its widest window region —
    needed to set up offscreen rendering (reference ``utils.py:6-28``).

    Returns ``(area, space, region)``.
    """
    areas = [a for a in bpy.context.screen.areas if a.type == "VIEW_3D"]
    if not areas:
        raise RuntimeError("No VIEW_3D area found; offscreen rendering needs a UI.")
    area = areas[0]
    regions = sorted(
        [r for r in area.regions if r.type == "WINDOW"],
        key=lambda r: r.width,
        reverse=True,
    )
    spaces = [s for s in area.spaces if s.type == "VIEW_3D"]
    if not regions or not spaces:
        raise RuntimeError("VIEW_3D area lacks window region or space.")
    return area, spaces[0], regions[0]


def _evaluated(objs, depsgraph):
    dg = depsgraph or bpy.context.evaluated_depsgraph_get()
    return [obj.evaluated_get(dg) for obj in objs]


def object_coordinates(*objs, depsgraph=None):
    """Nx3 object-space vertex coordinates, modifiers applied
    (reference ``utils.py:30-55``)."""
    coords = []
    for eo in _evaluated(objs, depsgraph):
        coords.extend(tuple(v.co) for v in eo.data.vertices)
    return np.array(coords)


def world_coordinates(*objs, depsgraph=None):
    """Nx3 world-space vertex coordinates, modifiers applied
    (reference ``utils.py:57-82``)."""
    coords = []
    for eo in _evaluated(objs, depsgraph):
        m = eo.matrix_world
        coords.extend(tuple(m @ v.co) for v in eo.data.vertices)
    return np.array(coords)


def bbox_world_coordinates(*objs, depsgraph=None):
    """Nx3 world-space bounding-box corners (8 per object)
    (reference ``utils.py:84-109``)."""
    coords = []
    for eo in _evaluated(objs, depsgraph):
        m = eo.matrix_world
        coords.extend(tuple(m @ Vector(c)) for c in eo.bound_box)
    return np.array(coords)


def compute_object_visibility(obj, cam, N=25, scene=None, view_layer=None, dist=None, rng=None):
    """Monte-Carlo visibility fraction of ``obj`` from camera ``cam`` via
    ray casting (reference ``utils.py:158-179``)."""
    scene = scene or bpy.context.scene
    vl = view_layer or bpy.context.view_layer
    rng = rng or np.random.default_rng()
    src = cam.bpy_camera.matrix_world.translation
    dist = dist or 1.70141e38
    cam_inv = cam.bpy_camera.matrix_world.inverted()

    ids = rng.integers(0, len(obj.data.vertices), size=N)
    visible = 0
    for idx in ids:
        dst_world = obj.matrix_world @ obj.data.vertices[int(idx)].co
        direction = (dst_world - src).normalized()
        dst_cam = cam_inv @ dst_world
        if dst_cam.z <= 0.0 and np.isfinite(np.asarray(direction)).all():
            hit, _, _, _, hit_obj, _ = scene.ray_cast(vl, src, direction, distance=dist)
            if hit and hit_obj == obj:
                visible += 1
    return visible / N


def scene_stats():
    """Active/orphaned object counts per data collection — debug aid
    (reference ``utils.py:181-192``; fixed: the reference iterates
    ``dir(bpy.data)`` strings and its isinstance check never matches)."""
    stats = {}
    for attr in dir(bpy.data):
        coll = getattr(bpy.data, attr, None)
        if isinstance(coll, bpy.types.bpy_prop_collection) and len(coll):
            orphaned = sum(1 for o in coll if getattr(o, "users", 1) == 0)
            stats[attr] = (len(coll) - orphaned, orphaned)
    return stats
