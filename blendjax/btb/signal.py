"""Minimal multicast callback registry (reference ``btb/signal.py:20-54``).

Used by :class:`blendjax.btb.animation.AnimationController` to expose its
lifecycle hooks (pre_frame, post_frame, ...).  Handlers may be registered
with pre-bound leading args; ``add`` returns a handle that unregisters.
"""

from __future__ import annotations

import functools


class Signal:
    """An ordered list of callables invoked with ``invoke(*args, **kwargs)``."""

    def __init__(self):
        self._slots = []

    def add(self, fn, *bound_args, **bound_kwargs):
        """Register ``fn``; returns a handle accepted by :meth:`remove`.

        Extra args are pre-bound before any invoke-time args, so
        ``sig.add(fn, x)`` then ``sig.invoke(y)`` calls ``fn(x, y)``.
        """
        if bound_args or bound_kwargs:
            fn = functools.partial(fn, *bound_args, **bound_kwargs)
        self._slots.append(fn)
        return fn

    def add_first(self, fn, *bound_args, **bound_kwargs):
        """Register ``fn`` ahead of every existing handler — for hooks
        that must observe/mutate state before the frame's regular
        handlers run (e.g. a scenario param push applying before the
        agent's action is prepared)."""
        if bound_args or bound_kwargs:
            fn = functools.partial(fn, *bound_args, **bound_kwargs)
        self._slots.insert(0, fn)
        return fn

    def remove(self, handle):
        self._slots.remove(handle)

    def clear(self):
        self._slots.clear()

    def invoke(self, *args, **kwargs):
        # iterate over a copy: handlers may (un)register during dispatch
        for fn in list(self._slots):
            fn(*args, **kwargs)

    def __len__(self):
        return len(self._slots)
