"""blendjax.btb — producer-side package, runs inside Blender's Python.

Mirrors the reference's ``blendtorch.btb`` surface
(``pkg_blender/blendtorch/btb/__init__.py:1-9``) so existing publisher
scripts port by changing the import line.  Attribute access is lazy (PEP
562): modules that need ``bpy``/``gpu`` only import when first touched, so
the package is importable (and unit-testable) outside Blender.
"""

__version__ = "0.1.0"

_LAZY = {
    # name -> (module, attr)
    "parse_blendtorch_args": ("blendjax.btb.arguments", "parse_blendtorch_args"),
    "parse_btargs": ("blendjax.btb.arguments", "parse_btargs"),
    "BlendJaxArgs": ("blendjax.btb.arguments", "BlendJaxArgs"),
    "Signal": ("blendjax.btb.signal", "Signal"),
    "AnimationController": ("blendjax.btb.animation", "AnimationController"),
    "OffScreenRenderer": ("blendjax.btb.offscreen", "OffScreenRenderer"),
    "Camera": ("blendjax.btb.camera", "Camera"),
    "DataPublisher": ("blendjax.btb.publisher", "DataPublisher"),
    "DuplexChannel": ("blendjax.btb.duplex", "DuplexChannel"),
    "BaseEnv": ("blendjax.btb.env", "BaseEnv"),
    "RemoteControlledAgent": ("blendjax.btb.env", "RemoteControlledAgent"),
}

_LAZY_MODULES = (
    "arguments",
    "signal",
    "animation",
    "offscreen",
    "camera",
    "camera_math",
    "publisher",
    "duplex",
    "env",
    "utils",
    "constants",
)


def __getattr__(name):
    import importlib

    if name in _LAZY:
        module, attr = _LAZY[name]
        value = getattr(importlib.import_module(module), attr)
        globals()[name] = value
        return value
    if name in _LAZY_MODULES:
        mod = importlib.import_module(f"blendjax.btb.{name}")
        globals()[name] = mod
        return mod
    raise AttributeError(f"module 'blendjax.btb' has no attribute {name!r}")


def __dir__():
    return sorted(set(list(globals()) + list(_LAZY) + list(_LAZY_MODULES)))
