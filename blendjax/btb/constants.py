"""Producer-side defaults (reference ``btb/constants.py:4``)."""

#: Default socket timeout inside Blender.  Shorter than the consumer side:
#: a stuck producer should fail fast rather than stall the animation loop.
DEFAULT_TIMEOUTMS = 5000
