"""bpy camera adapter: intrinsics/extrinsics + pixel-space annotations
(reference ``btb/camera.py:8-204``).

Thin wrapper around ``bpy.types.Camera`` delegating all math to the pure
:mod:`blendjax.btb.camera_math` (tested without Blender).  Produces the
``xy`` keypoint / bbox labels streamed alongside rendered frames.
"""

from __future__ import annotations

import numpy as np

from blendjax.btb import camera_math as cm

try:  # only inside Blender
    import bpy
except ImportError:  # pragma: no cover - exercised only in Blender
    bpy = None
try:
    from mathutils import Vector
except ImportError:  # pragma: no cover - exercised only in Blender
    Vector = None


class Camera:
    """Camera settings, matrices, and projection helpers.

    Params
    ------
    bpy_camera: bpy.types.Object | None
        Camera object; defaults to the scene camera.
    shape: (H, W) | None
        Target image shape; defaults to render settings.
    """

    def __init__(self, bpy_camera=None, shape=None):
        self.bpy_camera = bpy_camera or bpy.context.scene.camera
        self.shape = shape or Camera.shape_from_bpy()
        self.update_view_matrix()
        self.update_proj_matrix()

    def update_view_matrix(self):
        """Refresh the cached 4x4 view matrix (world -> camera)."""
        self.view_matrix = Camera.view_from_bpy(self.bpy_camera)

    def update_proj_matrix(self):
        """Refresh the cached 4x4 projection matrix (camera -> clip)."""
        self.proj_matrix = Camera.proj_from_bpy(self.bpy_camera, self.shape)

    @property
    def type(self):
        return self.bpy_camera.data.type

    @property
    def clip_range(self):
        return (self.bpy_camera.data.clip_start, self.bpy_camera.data.clip_end)

    @staticmethod
    def shape_from_bpy(bpy_render=None):
        """(H, W) from render settings incl. resolution percentage
        (reference ``camera.py:57-66``)."""
        render = bpy_render or bpy.context.scene.render
        scale = render.resolution_percentage / 100.0
        return (int(render.resolution_y * scale), int(render.resolution_x * scale))

    @staticmethod
    def view_from_bpy(bpy_camera):
        """View matrix = normalized world matrix inverted (reference
        ``camera.py:68-72``)."""
        camera = bpy_camera or bpy.context.scene.camera
        return np.asarray(camera.matrix_world.normalized().inverted())

    @staticmethod
    def proj_from_bpy(bpy_camera, shape):
        """Projection via ``calc_matrix_camera`` on the evaluated depsgraph
        (reference ``camera.py:74-82``)."""
        camera = bpy_camera or bpy.context.scene.camera
        shape = shape or Camera.shape_from_bpy()
        return np.asarray(
            camera.calc_matrix_camera(
                bpy.context.evaluated_depsgraph_get(), x=shape[1], y=shape[0]
            )
        )

    # -- projections (pure math, see camera_math) ---------------------------

    def world_to_ndc(self, xyz_world, return_depth=False):
        return cm.world_to_ndc(
            xyz_world, self.view_matrix, self.proj_matrix, return_depth=return_depth
        )

    def ndc_to_pixel(self, ndc, origin="upper-left"):
        return cm.ndc_to_pixel(ndc, self.shape, origin=origin)

    def object_to_pixel(self, *objs, return_depth=False, origin="upper-left"):
        """Pixel coordinates of all vertices of the given objects
        (reference ``camera.py:138-162``)."""
        from blendjax.btb.utils import world_coordinates

        return cm.project_points(
            world_coordinates(*objs),
            self.view_matrix,
            self.proj_matrix,
            self.shape,
            origin=origin,
            return_depth=return_depth,
        )

    def bbox_object_to_pixel(self, *objs, return_depth=False, origin="upper-left"):
        """Pixel coordinates of the bbox corners of the given objects
        (reference ``camera.py:165-189``)."""
        from blendjax.btb.utils import bbox_world_coordinates

        return cm.project_points(
            bbox_world_coordinates(*objs),
            self.view_matrix,
            self.proj_matrix,
            self.shape,
            origin=origin,
            return_depth=return_depth,
        )

    def look_at(self, look_at=None, look_from=None):
        """Aim the camera: -Z at target, Y up (reference ``camera.py:191-204``)."""
        if look_from is None:
            look_from = self.bpy_camera.location
        if look_at is None:
            look_at = Vector((0.0, 0.0, 0.0))
        direction = Vector(look_at) - Vector(look_from)
        rot_quat = direction.to_track_quat("-Z", "Y")
        self.bpy_camera.rotation_euler = rot_quat.to_euler()
        self.bpy_camera.location = look_from
        self.update_view_matrix()
