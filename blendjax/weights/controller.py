"""WeightBusController: metric-driven canary promote / rollback.

The human-free half of the rollout discipline (docs/weight_bus.md
"Canary and rollback"): the gateway can *route* a fraction of fresh
episodes to replicas at a new weight version and *measure* each
version's request/error/latency profile
(:meth:`~blendjax.serve.gateway.ServeGateway.version_stats`); this
controller closes the loop —

- a **new version** appearing in the fleet (scraped per-replica
  ``weight_version``) opens a canary window at ``fraction``;
- a canary that stays **healthy** through ``healthy_window_s`` with at
  least ``min_requests`` observed is **promoted** (it becomes the
  stable version; counted ``weight_canary_promotions``);
- a canary whose error rate exceeds ``max_error_rate`` or whose p99
  exceeds ``max_p99_x`` times the stable version's is **rolled back**:
  canary routing stops (``weight_canary_rollbacks``), the version is
  rejected for fresh traffic, and — when a
  :class:`~blendjax.weights.bus.WeightPublisher` is attached — the
  stable version's weights are re-published under a fresh higher
  version id (``weight_rollback_publishes``), rolling the whole
  subscribed fleet *forward* to the old weights;
- the **first** version ever seen has no baseline to canary against
  and is adopted as stable directly.

Drive it by calling :meth:`tick` from your own loop (deterministic —
what the tests do) or :meth:`start` a daemon thread.
"""

from __future__ import annotations

import logging
import threading
import time

logger = logging.getLogger("blendjax")


class WeightBusController:
    """Automated canary lifecycle over one
    :class:`~blendjax.serve.gateway.ServeGateway` (and optionally the
    :class:`~blendjax.weights.bus.WeightPublisher` to drive rollback
    republishes through).

    Params
    ------
    gateway: ServeGateway
        The in-process gateway whose canary routing and per-version
        metrics this controller drives.
    publisher: WeightPublisher | None
        When given, a rollback also re-publishes the stable version's
        weights (fresh higher version id) so subscribed replicas roll
        forward to the old weights instead of serving the rejected ones
        forever.
    fraction: float
        Share of fresh episodes routed to the canary version while a
        window is open.
    healthy_window_s: float
        How long a canary must stay healthy before promotion.
    min_requests: int
        Canary replies observed before any verdict (promote OR
        rollback) — one slow request must not roll a version back.
    max_error_rate: float
        Canary error-reply fraction above which it rolls back.
    max_p99_x: float
        Canary p99 over stable p99 above which it rolls back (skipped
        while the stable version has no latency history).
    verdict_timeout_s: float
        Liveness bound on the window itself: a canary that has NOT
        accumulated ``min_requests`` replies by this deadline — while
        the fleet served enough traffic that its ``fraction`` share
        should have — is rolled back as wedged/unreachable (a
        crash-looping canary replica never replies, so no error-rate
        or p99 verdict would ever fire, and an open window holds
        unknown-version replicas out of fresh traffic forever).  When
        the whole fleet was idle there is nothing to judge by and the
        window stays open.
    """

    def __init__(self, gateway, publisher=None, *, fraction=0.25,
                 healthy_window_s=2.0, min_requests=20,
                 max_error_rate=0.05, max_p99_x=1.5,
                 verdict_timeout_s=30.0):
        self.gateway = gateway
        self.publisher = publisher
        self.fraction = float(fraction)
        self.healthy_window_s = float(healthy_window_s)
        self.min_requests = int(min_requests)
        self.max_error_rate = float(max_error_rate)
        self.max_p99_x = float(max_p99_x)
        self.verdict_timeout_s = float(verdict_timeout_s)
        self._canary_t0 = None
        self._base = {}           # version -> (requests, errors) at t0
        self._thread = None
        self._stop = None

    # -- state views ---------------------------------------------------------

    def _fleet_versions(self):
        """Healthy replicas' scraped weight versions (None filtered)."""
        return [
            v for v in self.gateway.fleet_versions().values()
            if v is not None
        ]

    def _delta(self, stats, version):
        """(requests, errors) for ``version`` since the canary window
        opened."""
        rec = stats.get(version)
        if rec is None:
            return 0, 0
        b_req, b_err = self._base.get(version, (0, 0))
        return rec["requests"] - b_req, rec["errors"] - b_err

    # -- the decision tick ---------------------------------------------------

    def _open_window(self, version):
        """Start a canary window at ``version``: snapshot every
        version's (requests, errors) as the diff baseline, stamp the
        clock, flip the gateway's routing split."""
        self._base = {
            v: (rec["requests"], rec["errors"])
            for v, rec in self.gateway.version_stats().items()
        }
        self._canary_t0 = time.monotonic()
        self.gateway.canary(version, self.fraction)
        logger.info("weight controller: canary v%d at %.0f%%",
                    version, 100 * self.fraction)
        return "canary"

    def tick(self):
        """One control decision; returns the action taken
        (``"canary" | "promote" | "rollback" | None``)."""
        gw = self.gateway
        versions = self._fleet_versions()
        newest = max(versions) if versions else None
        stable = gw.stable_version
        if gw.canary_version is None:
            if newest is None:
                return None
            if stable is None:
                # first version the fleet ever reports: no baseline to
                # canary against — adopt it as the stable reference
                gw.set_stable(newest)
                return None
            if newest <= stable or newest == gw.rejected_version:
                return None
            return self._open_window(newest)
        # a window is open
        canary_v = gw.canary_version
        if newest is not None and newest > canary_v:
            # superseded mid-window: restart the window at the newest
            # version (the old canary never gets a verdict)
            return self._open_window(newest)
        stats = gw.version_stats()
        c_req, c_err = self._delta(stats, canary_v)
        regression = None
        if c_req < self.min_requests:
            if time.monotonic() - self._canary_t0 \
                    < self.verdict_timeout_s:
                return None
            fleet_req = sum(
                self._delta(stats, v)[0] for v in stats
            )
            if fleet_req * self.fraction < self.min_requests:
                # the whole fleet was (near) idle: nothing to judge a
                # healthy canary against either — keep the window open
                return None
            regression = (
                f"{c_req} canary replies in {self.verdict_timeout_s:g}s"
                f" while the fleet served {fleet_req} — canary wedged "
                "or unreachable"
            )
        elif (c_err / c_req) > self.max_error_rate:
            regression = (f"error rate {c_err / c_req:.3f} > "
                          f"{self.max_error_rate}")
        else:
            c_p99 = (stats.get(canary_v) or {}).get("p99_ms", 0.0)
            s_p99 = (stats.get(stable) or {}).get("p99_ms", 0.0)
            if s_p99 > 0 and c_p99 > self.max_p99_x * s_p99:
                regression = (f"p99 {c_p99:.1f}ms > {self.max_p99_x}x "
                              f"stable {s_p99:.1f}ms")
        if regression is not None:
            gw.rollback()
            logger.warning("weight controller: canary v%d rolled back "
                           "(%s)", canary_v, regression)
            if self.publisher is not None and stable is not None:
                try:
                    # the republished (old-weights, new-id) version IS
                    # the fleet's new stable reference — without this,
                    # the next tick would canary the republication
                    # against the version it just rolled back
                    gw.set_stable(self.publisher.republish(stable))
                except KeyError:
                    logger.warning(
                        "weight controller: stable v%d aged out of "
                        "publisher history; fleet keeps serving its "
                        "adopted weights", stable,
                    )
            return "rollback"
        if time.monotonic() - self._canary_t0 >= self.healthy_window_s:
            gw.promote()
            logger.info("weight controller: canary v%d promoted",
                        canary_v)
            return "promote"
        return None

    # -- background driving --------------------------------------------------

    def start(self, interval_s=0.25):
        if self._thread is not None:
            return self
        self._stop = threading.Event()

        def loop():
            while not self._stop.is_set():
                try:
                    self.tick()
                except Exception:  # noqa: BLE001 - controller survives
                    logger.exception("weight controller tick failed")
                self._stop.wait(interval_s)

        self._thread = threading.Thread(
            target=loop, daemon=True, name="bjx-weight-controller"
        )
        self._thread.start()
        return self

    def stop(self):
        if self._thread is not None:
            self._stop.set()
            self._thread.join(timeout=5)
            self._thread = None
            self._stop = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()
        return False
