"""WeightBus: live versioned weight publication, learner -> serve tier.

The missing connective tissue of the flywheel (ROADMAP #2): the learner
and the :class:`~blendjax.serve.server.PolicyServer` share model code
but never talked — the serve tier deployed nothing.  The bus closes the
loop with the Podracer parameter-streaming pattern (arXiv:2104.06272)
under production rollout discipline (arXiv:2605.25645):

- :class:`WeightPublisher` — a ROUTER socket any number of subscribers
  dial.  :meth:`~WeightPublisher.publish` snapshots a parameter pytree
  into a versioned, checksummed :class:`~blendjax.weights.snapshot.
  Snapshot` (monotonic version id, learner step, per-leaf digest),
  optionally quantizes it for the wire (:func:`blendjax.ops.quant.
  quantize_for_wire` — attention/MLP/head weights go int8, layernorms
  and biases ride the float fallback), chunks large leaves, and streams
  ``begin``/``chunk``/``commit`` to every known subscriber.  Late
  joiners ask (``wb_sync``) and get the latest FULL snapshot before
  riding leaf-level deltas; a bounded history serves
  :meth:`~WeightPublisher.republish` — the rollback primitive: a prior
  version's weights re-published under a fresh, higher version id
  (versions never run backwards, even to go back);
- :class:`WeightSubscriber` — the server-side half, polled from the
  serve tick loop (never a thread of its own: the hot-swap point must
  be *between* ticks).  It drains its DEALER socket non-blocking,
  assembles and digest-verifies snapshots, discards torn ones
  (``weight_torn_discarded``) and mismatched ones
  (``weight_digest_rejected``) without ever half-applying, and
  re-requests a full sync on a missed delta base or a silent publisher
  respawn.  A publisher death is **invisible to serve clients**: the
  server keeps serving the last good version.

Run a standalone publisher process (the chaos tests SIGKILL it
mid-snapshot)::

    python -m blendjax.weights.bus --address tcp://127.0.0.1:24200 \
        --obs-dim 8 --interval-ms 500

It publishes ``{"w": ...}`` linear-model trees whose weights derive
deterministically from the version id (:func:`linear_tree`), so a test
can verify exactly which version a serving prediction came from.

See docs/weight_bus.md.
"""

from __future__ import annotations

import argparse
import logging
import signal
import threading
import time

import numpy as np

from blendjax import wire
from blendjax.utils.timing import fleet_counters
from blendjax.weights.snapshot import (
    DEFAULT_CHUNK_BYTES,
    Snapshot,
    SnapshotAssembler,
    snapshot_messages,
)

logger = logging.getLogger("blendjax")

#: Bound on remembered subscriber identities (idents of dead
#: subscribers age out oldest-first; a live one re-registers with every
#: sync/ack).
SUBSCRIBER_CAP = 256

#: How often an idle subscriber re-announces itself (``wb_sync``): the
#: keepalive that heals a silently-respawned publisher (fresh ROUTER,
#: empty subscriber table) and doubles as the late-joiner catch-up —
#: the publisher answers with a tiny version note when nothing is new.
RESYNC_INTERVAL_S = 2.0


def linear_tree(version, obs_dim, out_dim=None):
    """The standalone publisher CLI's deterministic payload: a
    ``{"w": (obs_dim, out_dim) f32}`` tree seeded by the VERSION id
    (the same recipe as ``LinearModel(seed=version)``), so a chaos test
    can assert from a serving prediction alone which version a replica
    is at."""
    rng = np.random.default_rng(int(version))
    return {"w": rng.standard_normal(
        (int(obs_dim), int(out_dim or obs_dim))
    ).astype(np.float32)}


class WeightPublisher:
    """The learner-side half of the bus (module docstring).

    Params
    ------
    address: str
        Endpoint to bind (``tcp://host:*`` binds an ephemeral port;
        resolved endpoint on :attr:`address`).
    quantize: str | None
        Quantize snapshots for the wire via :func:`blendjax.ops.quant.
        quantize_for_wire` (``"seqformer"`` / ``"policy"`` /
        ``"detector"``); the subscribing server must serve the matching
        precision (``--int8``).  None ships float.
    chunk_bytes: int
        Chunk payload size (large leaves span chunks).
    history: int
        Published snapshots kept for late-joiner syncs and
        :meth:`republish` rollbacks.
    version_base: int | None
        Version ids start above this.  The default (None) derives the
        base from the wall clock, so ANY respawned publisher — embedded
        in a restarted learner, or the standalone process — starts
        above a predecessor that published less than one version per
        second, keeping versions monotonic across process deaths
        (subscribers never adopt backwards).  Pass an explicit base for
        deterministic version ids (tests).
    chunk_sleep_ms: float
        Sleep between streamed chunks (0 = off) — the chaos knob that
        widens the mid-snapshot kill window.
    """

    def __init__(self, address="tcp://127.0.0.1:*", *, quantize=None,
                 chunk_bytes=DEFAULT_CHUNK_BYTES, history=4,
                 version_base=None, chunk_sleep_ms=0.0, counters=None,
                 timer=None, context=None):
        import zmq

        self.quantize = quantize
        self.chunk_bytes = int(chunk_bytes)
        self.history_depth = max(1, int(history))
        self.chunk_sleep_ms = float(chunk_sleep_ms)
        self.counters = counters if counters is not None else fleet_counters
        self.timer = timer
        self._ctx = context or zmq.Context.instance()
        self._lock = threading.RLock()
        self._sock = self._ctx.socket(zmq.ROUTER)
        self._sock.setsockopt(zmq.LINGER, 0)
        # a full pipe to one dead subscriber must cost THAT stream, not
        # block the learner's publish under the lock
        self._sock.setsockopt(zmq.SNDTIMEO, 100)
        if address.endswith(":*") or address.endswith(":0"):
            base = address.rsplit(":", 1)[0]
            port = self._sock.bind_to_random_port(base)
            self.address = f"{base}:{port}"
        else:
            self._sock.bind(address)
            self.address = address
        self._poller = zmq.Poller()
        self._poller.register(self._sock, zmq.POLLIN)
        self._version = (int(time.time()) if version_base is None
                         else int(version_base))
        self._history = []        # [(version, Snapshot)] newest last
        self._subs = {}           # ident -> last acked version (or None)
        self._hold = None         # chaos: (version, after_chunks)
        self._serve_thread = None
        self._serve_stop = None

    # -- publishing ----------------------------------------------------------

    @property
    def version(self):
        """The latest published version id (``version_base`` before the
        first publish)."""
        return self._version

    @property
    def subscribers(self):
        """``{ident bytes: last acked version}`` snapshot."""
        with self._lock:
            return dict(self._subs)

    def _latest(self):
        return self._history[-1] if self._history else None

    def publish(self, params, step=0, *, model=None):
        """Snapshot ``params`` (quantized for the wire when configured)
        under the next version id and stream it — as a leaf-level delta
        against the previous publish where digests allow — to every
        known subscriber.  Returns the version id."""
        if self.quantize is not None:
            from blendjax.ops.quant import quantize_for_wire

            params = quantize_for_wire(params, self.quantize)
        t0 = time.perf_counter()
        with self._lock:
            self._version += 1
            snap = Snapshot.from_params(params, self._version, step,
                                        model=model)
            prev = self._latest()
            msgs = snapshot_messages(snap, prev=prev,
                                     chunk_bytes=self.chunk_bytes)
            self._history.append(snap)
            del self._history[:-self.history_depth]
            for ident in list(self._subs):
                self._stream(ident, msgs)
            self.counters.incr("weight_published")
            self.counters.incr(
                "weight_publish_bytes",
                msgs[0]["total_bytes"] * max(1, len(self._subs)),
            )
        if self.timer is not None:
            self.timer.add("weight_publish", time.perf_counter() - t0)
        return snap.version

    def republish(self, version):
        """The rollback primitive: re-publish the weights of a PRIOR
        version under a fresh, higher version id (version ids are
        monotonic — the fleet rolls *forward* to the old weights).
        Raises ``KeyError`` when the version has aged out of history."""
        with self._lock:
            old = next((s for s in self._history
                        if s.version == int(version)), None)
            if old is None:
                raise KeyError(
                    f"version {version} not in publisher history "
                    f"({[s.version for s in self._history]}); raise "
                    "history="
                )
            self._version += 1
            snap = Snapshot(self._version, old.step, old.leaves,
                            model=old.model, digests=old.digests)
            msgs = snapshot_messages(snap, prev=self._latest(),
                                     chunk_bytes=self.chunk_bytes)
            self._history.append(snap)
            del self._history[:-self.history_depth]
            for ident in list(self._subs):
                self._stream(ident, msgs)
            self.counters.incr("weight_published")
            self.counters.incr("weight_rollback_publishes")
        return snap.version

    def _stream(self, ident, msgs):
        """One subscriber's message stream; a send failure abandons the
        stream (the subscriber's stall timeout tears it and its next
        sync catches up)."""
        import zmq

        for i, msg in enumerate(msgs):
            if self._hold is not None and msg.get("wb") == "chunk" \
                    and msg["version"] >= self._hold[0] \
                    and msg["seq"] >= self._hold[1]:
                # chaos hold: park mid-snapshot forever (the test
                # SIGKILLs us here — a deterministic torn stream)
                while True:
                    time.sleep(0.5)
            try:
                wire.send_message_router(self._sock, ident, msg,
                                         raw_buffers=True)
            except zmq.ZMQError:
                return
            if self.chunk_sleep_ms and i < len(msgs) - 1:
                time.sleep(self.chunk_sleep_ms / 1000.0)

    # -- subscriber requests -------------------------------------------------

    def poll(self, timeout_ms=0):
        """Answer pending subscriber requests (``wb_sync``/``wb_ack``).
        Thread-safe with :meth:`publish`; the standalone process wraps
        it in :meth:`serve_forever`, an embedded publisher (inside a
        learner) calls :meth:`start` for a daemon thread."""
        import zmq

        if not self._poller.poll(timeout_ms):
            return 0
        n = 0
        with self._lock:
            while True:
                try:
                    ident, msg = wire.recv_message_router(
                        self._sock, flags=zmq.NOBLOCK
                    )
                except zmq.Again:
                    return n
                except zmq.ZMQError:
                    raise
                except Exception:  # noqa: BLE001 - rogue peer survives
                    continue
                n += 1
                cmd = msg.get("cmd")
                if cmd == "wb_ack":
                    if ident in self._subs:
                        # pop+reinsert: every sync/ack refreshes the
                        # ident's age, so the cap eviction below is
                        # LRU — churn of dead idents cannot evict a
                        # live, acking subscriber
                        self._subs.pop(ident)
                        self._subs[ident] = msg.get("version")
                    continue
                if cmd != "wb_sync":
                    continue
                self._subs[ident] = self._subs.pop(ident, None)
                while len(self._subs) > SUBSCRIBER_CAP:
                    self._subs.pop(next(iter(self._subs)))
                latest = self._latest()
                if latest is None:
                    self._reply(ident, {"wb": "none"})
                elif msg.get("have") == latest.version:
                    self._reply(ident, {"wb": "version",
                                        "version": latest.version})
                else:
                    # late joiner / re-sync: the FULL latest snapshot
                    # (no delta — we cannot know what base it holds)
                    self.counters.incr("weight_syncs")
                    self._stream(ident, snapshot_messages(
                        latest, prev=None, chunk_bytes=self.chunk_bytes
                    ))

    def _reply(self, ident, msg):
        import zmq

        try:
            wire.send_message_router(self._sock, ident, msg)
        except zmq.ZMQError:
            pass

    def serve_forever(self, stop_event=None, poll_ms=50):
        import zmq

        while stop_event is None or not stop_event.is_set():
            try:
                self.poll(poll_ms)
            except zmq.ZMQError:
                return  # socket closed under us: clean shutdown

    def start(self, poll_ms=50):
        """Serve subscriber requests from a daemon thread (re-startable
        after :meth:`stop`)."""
        if self._serve_thread is not None:
            return self
        self._serve_stop = threading.Event()
        self._serve_thread = threading.Thread(
            target=self.serve_forever,
            kwargs={"stop_event": self._serve_stop, "poll_ms": poll_ms},
            daemon=True, name="bjx-weight-publisher",
        )
        self._serve_thread.start()
        return self

    def stop(self):
        """Stop the serve thread (the socket stays bound: publishes
        still stream, but syncs go unanswered until :meth:`start`)."""
        if self._serve_thread is not None:
            self._serve_stop.set()
            self._serve_thread.join(timeout=5)
            self._serve_thread = None
            self._serve_stop = None

    def close(self):
        self.stop()
        try:
            self._sock.close(0)
        except Exception:  # noqa: BLE001 - shutdown best-effort
            pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


class WeightSubscriber:
    """The serving-side half of the bus: polled (never threaded) from
    the server's tick loop, so a verified snapshot is staged off-tick
    and hot-swapped *between* ticks.

    ``counters``/``timer`` default to None and are inherited from the
    attaching :class:`~blendjax.serve.server.PolicyServer` (or fall
    back to the process-wide registry when used standalone)."""

    def __init__(self, address, *, model=None, counters=None, timer=None,
                 stall_timeout_s=5.0,
                 resync_interval_s=RESYNC_INTERVAL_S, context=None):
        import zmq

        self.address = address
        #: hosted-model id snapshots apply to (None = server default)
        self.model = model
        self.counters = counters
        self.timer = timer
        self._ctx = context or zmq.Context.instance()
        self.sock = self._ctx.socket(zmq.DEALER)
        self.sock.setsockopt(zmq.LINGER, 0)
        self.sock.connect(address)
        self._asm = SnapshotAssembler(stall_timeout_s=stall_timeout_s)
        self.resync_interval_s = float(resync_interval_s)
        self._next_sync = 0.0  # sync immediately on first poll
        self._next_stale_warn = 0.0

    @property
    def _ctrs(self):
        return self.counters if self.counters is not None \
            else fleet_counters

    @property
    def version(self):
        """Version of the last complete, verified snapshot (None before
        the first)."""
        return self._asm.version

    def _send(self, msg):
        import zmq

        try:
            wire.send_message_dealer(self.sock, msg,
                                     flags=zmq.DONTWAIT)
        except zmq.ZMQError:
            pass  # publisher gone; the next resync interval retries

    def request_sync(self):
        """Ask the publisher for the latest full snapshot (late-joiner
        catch-up; also the heal after a torn delta base)."""
        self._send({"cmd": "wb_sync", "have": self.version})
        self._next_sync = time.monotonic() + self.resync_interval_s

    def _warn_stale(self, version):
        """A publisher whose latest version sits BELOW our adopted one
        can never update this fleet (versions never adopt backwards) —
        usually a respawned publisher whose version base was not raised
        past its predecessor.  Warn, debounced: silently holding the
        last good version forever would be indistinguishable from a
        healthy idle bus."""
        now = time.monotonic()
        if now < self._next_stale_warn:
            return
        self._next_stale_warn = now + 5.0
        logger.warning(
            "weight subscriber (%s): publisher offers v%s but v%s is "
            "already adopted — versions never run backwards, so this "
            "publisher can NEVER update us (a respawned publisher must "
            "start above its predecessor; WeightPublisher's default "
            "wall-clock version_base does, an explicit low base does "
            "not).  Holding the last good version.",
            self.address, version, self.version,
        )

    def poll(self):
        """Drain the socket non-blocking; returns the NEWEST complete,
        digest-verified :class:`Snapshot` staged by this drain (or
        None).  Torn and digest-rejected streams are discarded and
        counted here — the caller only ever sees whole snapshots."""
        import zmq

        if self._asm.check_stalled() == "torn":
            self._ctrs.incr("weight_torn_discarded")
            self.request_sync()
        staged = None
        while True:
            try:
                msg = wire.recv_message_dealer(self.sock,
                                               flags=zmq.NOBLOCK)
            except zmq.Again:
                break
            except zmq.ZMQError:
                raise
            except Exception:  # noqa: BLE001 - undecodable frame
                self._ctrs.incr("weight_torn_discarded")
                continue
            if msg.get("wb") in ("none", "version"):
                v = msg.get("version")
                if v is not None and self.version is not None \
                        and v < self.version:
                    self._warn_stale(v)
                continue
            t0 = time.perf_counter()
            snap, reason = self._asm.feed(msg)
            if reason == "torn":
                self._ctrs.incr("weight_torn_discarded")
            elif reason == "stale":
                self._warn_stale(int(msg.get("version", -1)))
            elif reason == "digest":
                self._ctrs.incr("weight_digest_rejected")
                self.request_sync()
            elif reason == "need_full":
                self.request_sync()
            if snap is not None:
                if self.timer is not None:
                    self.timer.add("weight_assemble",
                                   time.perf_counter() - t0)
                staged = snap  # newest wins within one drain
                self._send({"cmd": "wb_ack", "version": snap.version})
        if time.monotonic() >= self._next_sync \
                and not self._asm.in_flight:
            # first-contact sync, publisher-respawn heal, and keepalive
            # in one: a publisher that already answered with our exact
            # version costs one tiny message per interval.  Decided
            # AFTER the drain and suppressed mid-assembly — a sync
            # fired while a stream is arriving buys a duplicate full
            # snapshot (a stream slower than the resync interval would
            # re-trigger one every interval); a stream that DIED
            # mid-assembly is check_stalled's to tear (which re-arms
            # the sync above)
            self.request_sync()
        return staged

    def close(self):
        try:
            self.sock.close(0)
        except Exception:  # noqa: BLE001 - shutdown best-effort
            pass


# ---------------------------------------------------------------------------
# standalone publisher process (chaos surface)
# ---------------------------------------------------------------------------


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="Standalone WeightPublisher: streams versioned "
                    "linear-model weight snapshots (version-seeded, so "
                    "a serving prediction identifies its version)."
    )
    ap.add_argument("--address", required=True)
    ap.add_argument("--obs-dim", type=int, default=8)
    ap.add_argument("--out-dim", type=int, default=None)
    ap.add_argument("--interval-ms", type=float, default=500.0)
    ap.add_argument("--publishes", type=int, default=0,
                    help="stop after N publishes (0 = run until "
                         "signalled)")
    ap.add_argument("--version-base", type=int, default=None,
                    help="version ids start above this; default derives "
                         "from the wall clock so a respawned publisher "
                         "stays monotonic past its predecessor")
    ap.add_argument("--chunk-bytes", type=int,
                    default=DEFAULT_CHUNK_BYTES)
    ap.add_argument("--chunk-sleep-ms", type=float, default=0.0)
    ap.add_argument("--hold-at-version", type=int, default=None,
                    help="chaos: park forever mid-snapshot once this "
                         "version's stream reaches --hold-after-chunks "
                         "(the test SIGKILLs the parked process)")
    ap.add_argument("--hold-after-chunks", type=int, default=1)
    ap.add_argument("--wait-subscribers", type=int, default=0,
                    help="block the first publish until this many "
                         "subscribers have announced themselves (tests "
                         "use it to make publish-vs-subscribe ordering "
                         "deterministic)")
    args = ap.parse_args(argv)
    logging.basicConfig(level=logging.INFO)
    pub = WeightPublisher(
        args.address, version_base=args.version_base,
        chunk_bytes=args.chunk_bytes,
        chunk_sleep_ms=args.chunk_sleep_ms,
    )
    if args.hold_at_version is not None:
        pub._hold = (args.hold_at_version, args.hold_after_chunks)
    stop = threading.Event()

    def _term(signum, frame):
        stop.set()

    signal.signal(signal.SIGTERM, _term)
    signal.signal(signal.SIGINT, _term)
    logger.info("weight publisher at %s (version base %d)",
                pub.address, pub.version)
    published = 0
    try:
        while not stop.is_set() and \
                len(pub.subscribers) < args.wait_subscribers:
            pub.poll(20)
        while not stop.is_set():
            v = pub.publish(
                linear_tree(pub.version + 1, args.obs_dim,
                            args.out_dim),
                step=published,
            )
            published += 1
            logger.info("published weights v%d", v)
            if args.publishes and published >= args.publishes:
                break
            deadline = time.monotonic() + args.interval_ms / 1000.0
            while not stop.is_set() and time.monotonic() < deadline:
                pub.poll(20)
    finally:
        pub.close()


if __name__ == "__main__":
    main()
