"""Versioned, checksummed parameter snapshots — the WeightBus payload.

A snapshot is one model's parameter pytree flattened into an ordered
``{path: ndarray}`` leaf map (dicts recurse by key, lists by ``#i``
index) with a monotonic **version id**, the learner ``step`` that
produced it, and a CRC **digest per leaf** plus one over the whole
byte stream.  On the wire a snapshot rides as a ``begin`` /
``chunk``* / ``commit`` message sequence (:func:`snapshot_messages`):

- ``begin`` carries the manifest — every shipped leaf's path, dtype,
  shape, byte count and digest, plus the paths *carried* unchanged
  from a ``base`` version (leaf-level **deltas**: a leaf whose digest
  matches the previous published version is named, not re-sent);
- each ``chunk`` carries one contiguous slice of the concatenated leaf
  byte stream (large leaves span chunks, small ones share them), so a
  multi-MB pytree never monopolizes the subscriber's serve loop for
  one giant recv;
- ``commit`` carries the whole-stream digest.

The receiving half is :class:`SnapshotAssembler`: it accepts the
message stream in order, discards **torn** snapshots (a superseding
``begin``, a sequence gap, a stalled stream) and **digest-mismatched**
ones (stream or per-leaf) without ever half-applying — the consumer
only ever sees complete, verified snapshots.  A delta whose base the
assembler does not hold is refused with ``need_full`` so the
subscriber can request a full catch-up (the late-joiner path).

See docs/weight_bus.md for the wire format and failure matrix.
"""

from __future__ import annotations

import time
import zlib

import numpy as np

#: Default chunk payload size: big enough that framing is noise, small
#: enough that one chunk never stalls a serving tick's poll slice.
DEFAULT_CHUNK_BYTES = 256 * 1024


def _crc(data, crc=0):
    return zlib.crc32(data, crc) & 0xFFFFFFFF


def leaf_digest(arr):
    """CRC32 over a leaf's dtype, shape AND bytes (a reshaped or recast
    leaf with identical bytes must not collide)."""
    arr = np.ascontiguousarray(arr)
    head = f"{arr.dtype.str}:{arr.shape}".encode()
    return _crc(arr.tobytes(), _crc(head))


def flatten_tree(tree, prefix=""):
    """Pytree (nested dicts/lists/tuples of arrays) -> ordered
    ``{path: np.ndarray}``.  Dict levels flatten by sorted key, list
    levels by ``#i`` index, joined with ``/`` — deterministic order, so
    the byte stream (and its digest) is a pure function of the tree."""
    out = {}
    if isinstance(tree, dict):
        for k in sorted(tree):
            if not isinstance(k, str) or "/" in k or k.startswith("#"):
                raise ValueError(f"unflattenable dict key {k!r}")
            out.update(flatten_tree(tree[k], f"{prefix}{k}/"))
        return out
    if isinstance(tree, (list, tuple)):
        for i, v in enumerate(tree):
            out.update(flatten_tree(v, f"{prefix}#{i}/"))
        return out
    arr = np.asarray(tree)
    if arr.dtype == object:
        raise TypeError(f"object-dtype leaf at {prefix[:-1]!r}")
    out[prefix[:-1]] = arr
    return out


def unflatten_tree(leaves):
    """Inverse of :func:`flatten_tree`: ``{path: arr}`` -> nested
    dicts/lists (``#i`` components rebuild lists)."""
    root = {}
    for path, arr in leaves.items():
        parts = path.split("/")
        node = root
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = arr

    def build(node):
        if not isinstance(node, dict):
            return node
        if node and all(k.startswith("#") for k in node):
            idx = sorted(node, key=lambda k: int(k[1:]))
            if [int(k[1:]) for k in idx] != list(range(len(idx))):
                raise ValueError(f"gappy list indices: {sorted(node)}")
            return [build(node[k]) for k in idx]
        return {k: build(v) for k, v in node.items()}

    return build(root)


class Snapshot:
    """One complete, verified parameter snapshot."""

    __slots__ = ("version", "step", "model", "leaves", "digests")

    def __init__(self, version, step, leaves, *, model=None,
                 digests=None):
        self.version = int(version)
        self.step = int(step)
        self.model = model
        #: ordered {path: C-contiguous np.ndarray}
        self.leaves = {
            p: np.ascontiguousarray(a) for p, a in leaves.items()
        }
        self.digests = digests or {
            p: leaf_digest(a) for p, a in self.leaves.items()
        }

    @classmethod
    def from_params(cls, params, version, step=0, *, model=None):
        return cls(version, step, flatten_tree(params), model=model)

    def tree(self):
        """The snapshot's pytree (what ``model.apply_weights`` takes)."""
        return unflatten_tree(self.leaves)

    @property
    def total_bytes(self):
        return sum(a.nbytes for a in self.leaves.values())


def snapshot_messages(snap, *, prev=None,
                      chunk_bytes=DEFAULT_CHUNK_BYTES):
    """The snapshot's wire messages (``begin``, ``chunk``*, ``commit``)
    as a list of dicts.  ``prev`` (the publisher's previously published
    :class:`Snapshot`) enables leaf-level deltas: leaves whose digest is
    unchanged ride as ``carry`` paths instead of bytes, and ``base``
    names the version the receiver must hold to fill them in."""
    shipped, carry = [], []
    for path, arr in snap.leaves.items():
        if prev is not None and prev.digests.get(path) == \
                snap.digests[path] and path in prev.leaves:
            carry.append(path)
        else:
            shipped.append(path)
    manifest = [
        [p, snap.leaves[p].dtype.str, list(snap.leaves[p].shape),
         int(snap.leaves[p].nbytes), snap.digests[p]]
        for p in shipped
    ]
    payload = b"".join(snap.leaves[p].tobytes() for p in shipped)
    chunk_bytes = max(1, int(chunk_bytes))
    nchunks = max(1, -(-len(payload) // chunk_bytes)) if payload else 0
    msgs = [{
        "wb": "begin",
        "version": snap.version,
        "step": snap.step,
        "model": snap.model,
        "base": prev.version if (prev is not None and carry) else None,
        "carry": carry if (prev is not None and carry) else [],
        "manifest": manifest,
        "carry_digests": (
            {p: snap.digests[p] for p in carry} if carry else {}
        ),
        "nchunks": nchunks,
        "total_bytes": len(payload),
    }]
    for seq in range(nchunks):
        msgs.append({
            "wb": "chunk",
            "version": snap.version,
            "seq": seq,
            "data": np.frombuffer(
                payload, np.uint8, offset=seq * chunk_bytes,
                count=min(chunk_bytes, len(payload) - seq * chunk_bytes),
            ),
        })
    msgs.append({
        "wb": "commit",
        "version": snap.version,
        "digest": _crc(payload),
    })
    return msgs


class SnapshotAssembler:
    """Reassemble ``begin``/``chunk``/``commit`` streams into verified
    :class:`Snapshot` objects.  Stateful: holds the last good snapshot
    as the delta base, and at most one in-flight assembly.

    :meth:`feed` returns one of
    ``(None, None)`` — message consumed, nothing completed;
    ``(snapshot, None)`` — a complete, digest-verified snapshot;
    ``(None, "torn" | "digest" | "need_full")`` — the in-flight
    assembly was discarded (the caller counts it; ``need_full`` also
    means: request a full snapshot, our delta base is missing).
    Torn or mismatched streams are *discarded*, never half-applied.
    """

    def __init__(self, *, stall_timeout_s=5.0):
        self.stall_timeout_s = float(stall_timeout_s)
        self.last = None          # last good Snapshot (the delta base)
        self._cur = None          # in-flight: dict of assembly state
        self._last_chunk_t = 0.0

    @property
    def version(self):
        return self.last.version if self.last is not None else None

    @property
    def in_flight(self):
        """True while an assembly is mid-stream (chunks still owed).
        The subscriber gates its periodic resync on this: a ``wb_sync``
        fired mid-assembly makes the publisher stream a duplicate full
        snapshot for nothing (and, were streams not serialized, its
        ``begin`` would tear the in-progress one).  A *dead* publisher
        mid-stream is :meth:`check_stalled`'s job, not the keepalive's.
        """
        return self._cur is not None

    def _discard(self, reason):
        self._cur = None
        return None, reason

    def check_stalled(self):
        """Poll-time tear detection: an assembly with no chunk for
        ``stall_timeout_s`` is torn (publisher died mid-stream) —
        discard it so the counter pins even before a successor
        publishes.  Returns the tear reason or None."""
        if self._cur is not None and self.stall_timeout_s > 0 and \
                time.monotonic() - self._last_chunk_t \
                > self.stall_timeout_s:
            self._cur = None
            return "torn"
        return None

    def feed(self, msg):
        kind = msg.get("wb")
        if kind == "begin":
            reason = None
            if self._cur is not None:
                # a superseding begin: the previous stream is torn
                reason = "torn"
                self._cur = None
            version = int(msg["version"])
            if self.last is not None and version <= self.last.version:
                # stale (re)publication — an old publisher's leftovers,
                # or a respawned publisher whose version base was not
                # raised past its predecessor: versions are monotonic,
                # never adopt backwards.  "stale" (when no assembly was
                # torn) lets the caller WARN: a persistently stale
                # publisher means the fleet is silently not updating
                return None, reason or (
                    "stale" if version < self.last.version else None
                )
            base = msg.get("base")
            carry = list(msg.get("carry") or [])
            if carry and (self.last is None
                          or self.last.version != base
                          or any(p not in self.last.leaves
                                 for p in carry)):
                # a delta whose base we do not hold (late joiner, or a
                # tear ate the base): refuse and ask for a full one
                self._cur = None
                return None, "need_full"
            self._cur = {
                "version": version,
                "step": int(msg.get("step", 0)),
                "model": msg.get("model"),
                "manifest": list(msg["manifest"]),
                "carry": carry,
                "carry_digests": dict(msg.get("carry_digests") or {}),
                "nchunks": int(msg["nchunks"]),
                "total_bytes": int(msg["total_bytes"]),
                "chunks": [],
                "next_seq": 0,
            }
            self._last_chunk_t = time.monotonic()
            return None, reason
        if kind == "chunk":
            cur = self._cur
            if cur is None or int(msg["version"]) != cur["version"]:
                return None, None  # stray chunk of a discarded stream
            if int(msg["seq"]) != cur["next_seq"]:
                return self._discard("torn")  # sequence gap
            cur["chunks"].append(np.asarray(msg["data"], np.uint8))
            cur["next_seq"] += 1
            self._last_chunk_t = time.monotonic()
            return None, None
        if kind == "commit":
            cur = self._cur
            if cur is None or int(msg["version"]) != cur["version"]:
                return None, None
            self._cur = None
            if cur["next_seq"] != cur["nchunks"]:
                return None, "torn"
            payload = b"".join(c.tobytes() for c in cur["chunks"])
            if len(payload) != cur["total_bytes"] or \
                    _crc(payload) != int(msg["digest"]):
                return None, "digest"
            leaves, digests, off = {}, {}, 0
            for path, dstr, shape, nbytes, digest in cur["manifest"]:
                arr = np.frombuffer(
                    payload, np.dtype(dstr), offset=off,
                    count=int(np.prod(shape, dtype=np.int64))
                    if shape else 1,
                ).reshape(shape).copy()
                off += int(nbytes)
                if leaf_digest(arr) != digest:
                    return None, "digest"
                leaves[path] = arr
                digests[path] = digest
            for path in cur["carry"]:
                leaves[path] = self.last.leaves[path]
                digests[path] = cur["carry_digests"].get(
                    path, self.last.digests[path]
                )
            snap = Snapshot(cur["version"], cur["step"], leaves,
                            model=cur["model"], digests=digests)
            self.last = snap
            return snap, None
        return None, None
