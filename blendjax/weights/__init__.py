"""WeightBus (docs/weight_bus.md): live versioned weight publication
from the learner to the serve tier — the flywheel's connective tissue.

:class:`~blendjax.weights.bus.WeightPublisher` snapshots parameter
pytrees into versioned, checksummed, chunked snapshots (quantized for
the wire when configured) and streams them to any number of
:class:`~blendjax.weights.bus.WeightSubscriber` halves, which
:class:`~blendjax.serve.server.PolicyServer` polls from its tick loop
and hot-swaps **between ticks** — KV-cache slots, episode leases and
in-flight exactly-once retries all survive the swap, and a torn or
digest-mismatched snapshot is discarded, never half-applied.  The
:class:`~blendjax.serve.gateway.ServeGateway` layers canary routing by
lease on top, and :class:`~blendjax.weights.controller.
WeightBusController` automates promote-after-healthy-window /
rollback-on-regression from the per-version metrics.

Public surface::

    from blendjax.weights import (
        WeightPublisher, WeightSubscriber, WeightBusController,
        Snapshot, SnapshotAssembler,
    )

Imports stay lazy (PEP 562) so the jax-free server process pays only
for what it touches.
"""

from __future__ import annotations

_EXPORTS = {
    "WeightPublisher": "blendjax.weights.bus",
    "WeightSubscriber": "blendjax.weights.bus",
    "linear_tree": "blendjax.weights.bus",
    "WeightBusController": "blendjax.weights.controller",
    "Snapshot": "blendjax.weights.snapshot",
    "SnapshotAssembler": "blendjax.weights.snapshot",
    "flatten_tree": "blendjax.weights.snapshot",
    "unflatten_tree": "blendjax.weights.snapshot",
    "snapshot_messages": "blendjax.weights.snapshot",
}

__all__ = sorted(_EXPORTS)


def __getattr__(name):
    if name in _EXPORTS:
        import importlib

        return getattr(importlib.import_module(_EXPORTS[name]), name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


def __dir__():
    return sorted(set(globals()) | set(_EXPORTS))
