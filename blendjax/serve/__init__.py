"""Policy-serving inference tier (docs/serving.md) — the system's third
workload family: train -> replay -> **serve**.

One :class:`~blendjax.serve.server.PolicyServer` process owns a model
(MLP policy, seqformer world model, or the jax-free linear stand-in)
and serves ``step()``/``reset()``/``close()`` to many concurrent
episode clients over the DEALER wire with **continuous batching**: the
admission queue drains every tick into bucketed batch sizes, one jitted
call serves the tick, and for stateful world models every live episode
holds a row in a **KV-cache slot pool** decoded at per-row positions
(``seqformer.init_cache(per_row=True)``).  Retries are exactly-once via
the ``wire.BTMID_KEY`` reply cache; ``--int8`` serves the
``ops/quant``-quantized model through the same code.

A fleet of replicas scales the tier out behind a
:class:`~blendjax.serve.gateway.ServeGateway` (ROUTER front, per-replica
DEALER backends): episode-lease affinity pins an episode's steps to the
replica owning its KV-cache row, fresh episodes spread by scraped load,
and a SIGKILLed replica respawned by the watchdog costs its episodes
one actionable stale-lease error before they resume via ``reset()``.

Public surface::

    from blendjax.serve import (
        PolicyServer, ServeClient, ServeRPCError, ServerProcess,
        ServerFleet, ServeGateway, start_gateway_thread,
        LinearModel, PolicyModel, SeqFormerModel, start_server_thread,
    )

Imports stay lazy (PEP 562) so ``ServeClient``-only consumers and the
jax-free ``LinearModel`` server process never pay the model stack.
"""

from __future__ import annotations

_EXPORTS = {
    "PolicyServer": "blendjax.serve.server",
    "LinearModel": "blendjax.serve.server",
    "PolicyModel": "blendjax.serve.server",
    "SeqFormerModel": "blendjax.serve.server",
    "ServerProcess": "blendjax.serve.server",
    "ServerFleet": "blendjax.serve.server",
    "start_server_thread": "blendjax.serve.server",
    "default_buckets": "blendjax.serve.server",
    "ServeClient": "blendjax.serve.client",
    "ServeRPCError": "blendjax.serve.client",
    "ServeGateway": "blendjax.serve.gateway",
    "start_gateway_thread": "blendjax.serve.gateway",
}

__all__ = sorted(_EXPORTS)


def __getattr__(name):
    if name in _EXPORTS:
        import importlib

        return getattr(importlib.import_module(_EXPORTS[name]), name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


def __dir__():
    return sorted(set(globals()) | set(_EXPORTS))
