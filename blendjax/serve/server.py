"""PolicyServer: continuous batching of ``step()`` over the DEALER wire.

ROADMAP #3 opens the system's third workload family (train -> replay ->
**serve**): production traffic means *inference*, and until now every
consumer owned its own model replica and stepped alone.  This module
puts ONE model behind the existing wire protocol and serves thousands
of concurrent episodes from it:

- **continuous batching** (the TPU-serving scheduling result,
  arXiv:2605.25645): an admission queue is drained every tick, pending
  ``step`` requests are padded to a **bucketed** batch size (XLA
  compiles once per bucket, not once per occupancy), ONE jitted model
  call serves the tick, and replies scatter back per client over the
  ROUTER socket;
- **KV-cache slot pool** for stateful world-model serving: every live
  episode holds a row in batched ``(S, ...)`` cache arrays, a slot
  allocator handles admission/eviction on episode end, and
  :func:`blendjax.models.seqformer.decode_step` runs with **per-row
  positions** (``init_cache(per_row=True)``) so one batched decode
  serves episodes at heterogeneous timesteps — parity with per-episode
  serial decode is the correctness bar (tests/test_serve.py);
- **exactly-once RPCs**: every request carries a ``wire.BTMID_KEY``
  correlation id and a fault-policy retry re-sends the SAME id; the
  server answers a retried mutating request (``step``/``reset``/
  ``close``) from a bounded reply cache instead of decoding twice —
  the ``RemoteControlledAgent`` reply-cache pattern pointed at
  inference.  A duplicate of a request still *queued* is dropped at
  admission (the original's reply answers both);
- an ``--int8`` path serves the model through
  :func:`blendjax.ops.quant.quantize_seqformer` /
  :func:`~blendjax.ops.quant.quantize_policy` — the same model code,
  int8 weights;
- the house telemetry vocabulary end-to-end: ``SERVE_EVENTS`` counters,
  ``SERVE_STAGES`` (queue_wait / batch_assemble / compute / reply)
  with latency histograms via :class:`~blendjax.utils.timing.StageTimer`,
  a ``telemetry`` RPC in the TelemetryHub merge shape (remote scrape
  like ``ReplayShard``), and trace spans riding ``BTMID_KEY``.

Run a server as a process (the ``--model linear`` stand-in is jax-free
and fast-starting, so chaos tests SIGKILL/respawn it cheaply)::

    python -m blendjax.serve.server --address tcp://127.0.0.1:24000 \
        --model seqformer --seed 0 --obs-dim 8 --slots 64 --length 128

or in-process via :func:`start_server_thread`, or supervised via
:class:`ServerProcess` (a launcher-compatible surface, so
:class:`~blendjax.btt.watchdog.FleetWatchdog` respawns a dead server
and clients resume after ``reset()``).  The **serial** mode
(``serial=True``: a REP socket answering one request per exchange,
batch size 1) is the baseline the benchmark's ``serve_batch_x``
compares continuous batching against.

See docs/serving.md.
"""

from __future__ import annotations

import argparse
import logging
import os
import signal
import subprocess
import sys
import threading
import time
from collections import OrderedDict, deque

import numpy as np

from blendjax import wire
from blendjax.btt import shm_rpc
from blendjax.obs.spans import make_span, now_us
from blendjax.utils.timing import StageTimer, fleet_counters

logger = logging.getLogger("blendjax")

#: Commands whose replies enter the exactly-once reply cache (they
#: mutate episode state — a retry must NOT re-execute them).
MUTATING_CMDS = ("step", "reset", "close")

#: Idle horizon after which a STATELESS episode leaves the admission
#: window's live-count (window *targeting* only — stateless steps are
#: never refused).  A client idle this long is not co-arriving within a
#: millisecond tick window, and without decay every crashed consumer
#: would inflate the target until every batch waits out its full
#: ``tick_ms``.  Stateful servers use ``slot_ttl_s`` eviction instead.
STATELESS_TTL_S = 30.0

#: Default bound on the reply cache.  Each client keeps at most one RPC
#: outstanding (ServeClient is blocking), so the cache must cover the
#: retry window of roughly the live client count — 1024 replies of a
#: few hundred bytes is comfortably larger than any sane fleet while
#: bounding server memory.
REPLY_CACHE_DEPTH = 1024


def drain_socket(recv, handle, counters, who, what):
    """Drain every message currently on a socket: ``recv()`` (NOBLOCK)
    until ``zmq.Again``, dispatching each to ``handle``.  One copy of
    the survival discipline the serve tier's three receive loops share
    (server front, gateway front, gateway replica backends): a closed
    socket propagates (the serve loop shuts down cleanly), an
    UNDECODABLE frame (garbling proxy, rogue peer) is dropped and
    counted — never fatal; the frames are consumed and the sender's
    retry re-sends intact bytes.  The same contract covers ``handle``:
    a malformed-but-decodable message (e.g. an unhashable correlation
    id — the wire is pickle, a rogue peer can send anything) must cost
    that message, not the serving thread."""
    import zmq

    while True:
        try:
            out = recv()
        except zmq.Again:
            return
        except zmq.ZMQError:
            raise  # socket closed: the outer loop shuts down
        except Exception as exc:  # noqa: BLE001 - the tier survives
            counters.incr("serve_errors")
            logger.warning(
                "%s: undecodable %s dropped (%s: %s)",
                who, what, type(exc).__name__, exc,
            )
            continue
        try:
            handle(out)
        except zmq.ZMQError:
            raise  # socket closed mid-handle: clean shutdown
        except Exception:  # noqa: BLE001 - the tier survives
            counters.incr("serve_errors")
            logger.exception("%s: handling a %s failed (dropped)",
                             who, what)


def _check_tree_like(cur, new, what):
    """WeightBus apply guard: a snapshot must match the served params'
    STRUCTURE and per-leaf shapes before it replaces them — adopting a
    drifted tree would destroy the last good weights and leave every
    subsequent jitted call failing, the exact outage the 'refused
    snapshots keep serving the last good version' contract forbids."""
    import jax

    cur_leaves, cur_def = jax.tree.flatten(cur)
    new_leaves, new_def = jax.tree.flatten(new)
    if cur_def != new_def:
        raise ValueError(
            f"published {what} snapshot structure does not match the "
            f"served params ({new_def} != {cur_def})"
        )
    for c, n in zip(cur_leaves, new_leaves):
        if tuple(np.shape(c)) != tuple(np.shape(n)):
            raise ValueError(
                f"published {what} snapshot leaf shape {np.shape(n)} "
                f"!= served {np.shape(c)}"
            )


def default_buckets(max_batch):
    """Powers of two up to ``max_batch`` (inclusive as the cap): each
    bucket is one XLA compilation, so requests pad to the next bucket
    instead of compiling per occupancy."""
    out = []
    b = 1
    while b < max_batch:
        out.append(b)
        b *= 2
    out.append(max_batch)
    return tuple(out)


# ---------------------------------------------------------------------------
# served models
# ---------------------------------------------------------------------------


class LinearModel:
    """Jax-free stateful stand-in: ``pred = obs @ W + pos`` with a
    per-slot position counter.  Deterministic from ``seed`` (a
    respawned process rebuilds the same weights), position-sensitive
    (a double-applied step shifts every later prediction, so
    exactly-once violations are *visible*), and import-cheap — the
    chaos tests SIGKILL/respawn servers of this model in well under a
    second.

    ``work_us`` adds a sleep-based per-ROW model-compute stand-in to
    ``step_rows`` (the same disclosed pattern as the RL bench's
    ``physics_us``): the gateway scale-out bench needs replicas whose
    per-request cost is real enough to be the bottleneck, without
    spinning CPU the 2-core CI box does not have.  Zero (the default)
    is byte-identical to the pre-knob model."""

    kind = "linear"

    def __init__(self, obs_dim=8, out_dim=None, slots=16, seed=0,
                 work_us=0):
        self.obs_dim = int(obs_dim)
        self.out_dim = int(out_dim or obs_dim)
        self.slots = int(slots)
        self.work_us = float(work_us)
        rng = np.random.default_rng(seed)
        self.w = rng.standard_normal(
            (self.obs_dim, self.out_dim)
        ).astype(np.float32)
        # +1: the pad row batched ticks scatter their padding into
        self.pos = np.zeros(self.slots + 1, np.int64)
        self.pad_slot = self.slots

    def apply_weights(self, tree):
        """WeightBus hot-swap: replace ``w`` from a published
        ``{"w": (obs_dim, out_dim)}`` tree.  Positions (the per-slot
        KV-cache stand-in) are untouched — live episodes continue at
        their timestep under the new weights."""
        w = np.asarray(tree["w"], np.float32)
        if w.shape != self.w.shape:
            raise ValueError(
                f"published w shape {w.shape} != served {self.w.shape}"
            )
        self.w = w

    def reset_rows(self, idx):
        self.pos[idx] = 0

    def step_rows(self, idx, obs):
        if self.work_us:
            # per-row cost: batching does not amortize model compute
            # away (a batched decode's FLOPs scale with occupancy)
            time.sleep(len(idx) * self.work_us / 1e6)
        pred = obs.astype(np.float32) @ self.w \
            + self.pos[idx, None].astype(np.float32)
        self.pos[idx] += 1
        return pred

    def prefill_rows(self, idx, prefix):
        """Admit a T-step prefix in one pass: the slot's position jumps
        to T and the return is the prediction the T'th serial step would
        have produced — the jax-free analogue of the seqformer's batched
        prefill, so gateway/prefill plumbing tests run without jax."""
        t = prefix.shape[0]
        self.pos[idx] = t
        return prefix[-1].astype(np.float32) @ self.w + np.float32(t - 1)


class PolicyModel:
    """Stateless MLP policy serving (:mod:`blendjax.models.policy`):
    one jitted ``logits`` per bucket, greedy (argmax) actions — the
    deterministic serving convention.  ``int8=True`` serves
    :func:`~blendjax.ops.quant.quantize_policy` output through the same
    ``logits`` body (per-weight-dict dispatch)."""

    kind = "policy"
    slots = 0  # stateless: no cache rows, reset is an accounting no-op
    pad_slot = 0

    def __init__(self, params, obs_dim, int8=False):
        import jax

        from blendjax.models import policy

        if int8:
            from blendjax.ops.quant import quantize_policy

            params = quantize_policy(params)
        self.params = params
        self.obs_dim = int(obs_dim)
        self.int8 = bool(int8)
        self._logits = jax.jit(policy.logits)

    def apply_weights(self, tree):
        """WeightBus hot-swap: adopt a published policy pytree (float,
        or ``quantize_policy`` output when this server is ``--int8`` —
        ``policy.logits`` dispatches per weight dict either way)."""
        import jax
        import jax.numpy as jnp

        if self.int8 and not any(
            "w_q" in lay for lay in tree.get("layers", [{}])
        ):
            raise ValueError(
                "int8 policy server got a float snapshot; publish with "
                "quantize='policy' (or serve float)"
            )
        _check_tree_like(self.params, tree, "policy")
        self.params = jax.tree.map(jnp.asarray, tree)

    def reset_rows(self, idx):
        pass

    def step_rows(self, idx, obs):
        return np.asarray(self._logits(self.params, obs))


class SeqFormerModel:
    """Stateful world-model serving: a slot pool of batched KV caches
    (``init_cache(per_row=True)``) over ``slots + 1`` rows — the extra
    row absorbs batch padding writes — stepped by ONE jitted gather ->
    ``decode_step`` (per-row positions) -> scatter per bucket size.

    ``int8=True`` serves :func:`~blendjax.ops.quant.quantize_seqformer`
    output — ``decode_step`` already dispatches per weight dict, so the
    same serving code runs both precisions."""

    kind = "seqformer"

    def __init__(self, params, slots, length, *, window=None,
                 compute_dtype=None, cache_dtype=None, int8=False):
        import jax
        import jax.numpy as jnp

        from blendjax.models import seqformer

        if int8:
            from blendjax.ops.quant import quantize_seqformer

            params = quantize_seqformer(params)
        self.params = params
        self.slots = int(slots)
        self.length = int(length)
        self.window = window
        self.int8 = bool(int8)
        self.pad_slot = self.slots
        emb = params["embed"]
        self.obs_dim = (
            emb["w"] if "w" in emb else emb["w_q"]
        ).shape[0]
        cdt = compute_dtype or jnp.float32
        self._cache = seqformer.init_cache(
            params, self.slots + 1, dtype=cache_dtype or cdt,
            length=self.length, per_row=True,
        )
        self._jnp = jnp

        def _step(params, cache, idx, obs):
            rows = {
                "pos": cache["pos"][idx],
                "k": [k[idx] for k in cache["k"]],
                "v": [v[idx] for v in cache["v"]],
            }
            pred, new = seqformer.decode_step(
                params, rows, obs, compute_dtype=cdt, window=window,
            )
            # scatter the stepped rows back; padding duplicates all
            # land on the pad row, whose contents are never read
            cache = {
                "pos": cache["pos"].at[idx].set(new["pos"]),
                "k": [c.at[idx].set(nk)
                      for c, nk in zip(cache["k"], new["k"])],
                "v": [c.at[idx].set(nv)
                      for c, nv in zip(cache["v"], new["v"])],
            }
            return pred, cache

        # one compilation per (bucket,) shape — the bucket/recompile
        # tradeoff the admission queue pads for
        self._step = jax.jit(_step)

        def _prefill(params, cache, row, prefix):
            # ONE teacher-forced pass fills the slot's KV rows (the
            # standard prefill/decode split, exactly rollout()'s
            # prefill phase) instead of T serial decode_steps.  k/v
            # are rotated before the sink, so the cache holds the same
            # bytes serial decode would have written; positions past
            # the ring keep only the tail that fits, placed at each
            # position's ring slot.
            from blendjax.parallel.ring_attention import full_attention

            kvs = []
            preds, _ = seqformer._forward(
                params, prefix[None],
                lambda q, k, v: full_attention(
                    q, k, v, causal=True, window=window
                ),
                cdt, "dense", 2, 1.25, kv_sink=kvs,
            )
            t0 = prefix.shape[0]
            ring = cache["k"][0].shape[1]
            keep_n = min(t0, ring)
            slots_ax = (jnp.arange(keep_n) + (t0 - keep_n)) % ring
            new = {"pos": cache["pos"].at[row].set(t0), "k": [], "v": []}
            for i, (k, v) in enumerate(kvs):
                new["k"].append(cache["k"][i].at[row[0], slots_ax].set(
                    k[0, t0 - keep_n:].astype(cache["k"][i].dtype)
                ))
                new["v"].append(cache["v"][i].at[row[0], slots_ax].set(
                    v[0, t0 - keep_n:].astype(cache["v"][i].dtype)
                ))
            return preds[0, -1], new

        # one compilation per prefix LENGTH (prefix rows are real
        # observations — padding them would write fabricated positions
        # into the cache, so lengths are not bucketed)
        self._prefill = jax.jit(_prefill)

    def prefill_rows(self, idx, prefix):
        """Admit a T-step observation prefix into slot ``idx`` with one
        teacher-forced batched pass (vs T serial ``decode_step``s —
        parity within 1e-5, tests/test_serve.py).  Returns the
        prediction for position T (what the T'th serial step would have
        returned); the slot's next ``step`` decodes at position T."""
        t0 = int(prefix.shape[0])
        if t0 > self.length:
            # the teacher-forced pass attends the WHOLE prefix; serial
            # decode through a ring of `length` slots would only see
            # the last `length` (or `window`) positions — refuse the
            # configs where the two paths cannot agree
            if self.window is None or self.window > self.length:
                raise ValueError(
                    f"prefix of {t0} steps exceeds the {self.length}-slot "
                    "cache ring (and no window bounds attention): raise "
                    "length= or serve a windowed model"
                )
        if "pos" in self.params and t0 > self.params["pos"].shape[0]:
            raise ValueError(
                f"prefix of {t0} steps exceeds the learned position "
                f"table ({self.params['pos'].shape[0]}); use "
                "pos_encoding='rope' for longer prefixes"
            )
        pred, self._cache = self._prefill(
            self.params, self._cache, self._jnp.asarray(idx),
            self._jnp.asarray(prefix),
        )
        return np.asarray(pred)

    def apply_weights(self, tree):
        """WeightBus hot-swap: adopt a published seqformer pytree (the
        precision this server was built for — float, or
        ``quantize_seqformer`` output under ``--int8``).  The KV-cache
        slot pool is untouched: live episodes keep their rows, leases
        and positions, and the next tick decodes them under the new
        weights (the standard online-learning semantics — the cache
        holds the OLD weights' keys/values until positions ring past
        them, exactly as a learner's own rollout cache would)."""
        import jax
        import jax.numpy as jnp

        emb = tree.get("embed", {})
        if self.int8 != ("w_q" in emb):
            raise ValueError(
                "published snapshot precision (int8=%s) != served "
                "precision (int8=%s); align the publisher's quantize= "
                "with the server's --int8" % ("w_q" in emb, self.int8)
            )
        _check_tree_like(self.params, tree, "seqformer")
        self.params = jax.tree.map(jnp.asarray, tree)

    def reset_rows(self, idx):
        # rewinding pos to 0 is sufficient: _attn_one masks by each
        # slot's absolute position, so the stale k/v rows of the slot's
        # previous tenant sit at negative positions and never attend
        self._cache["pos"] = self._cache["pos"].at[
            self._jnp.asarray(idx)
        ].set(0)

    def step_rows(self, idx, obs):
        pred, self._cache = self._step(
            self.params, self._cache, self._jnp.asarray(idx),
            self._jnp.asarray(obs),
        )
        return np.asarray(pred)  # fence: compute timing stays honest


# ---------------------------------------------------------------------------
# the server
# ---------------------------------------------------------------------------


class _Pending:
    __slots__ = ("ident", "mid", "msg", "t_enq", "span_trace", "t0_us",
                 "mstate")

    def __init__(self, ident, mid, msg, span_trace, t0_us, mstate):
        self.ident = ident
        self.mid = mid
        self.msg = msg
        self.t_enq = time.perf_counter()
        self.span_trace = span_trace
        self.t0_us = t0_us
        self.mstate = mstate


class _ModelState:
    """One hosted model's serving state: its slot pool (or stateless
    episode registry) — multi-model servers keep one per model id, so
    one model's slot exhaustion can never deny another's resets."""

    __slots__ = ("mid", "model", "free", "live", "stateless_eps",
                 "weight_version")

    def __init__(self, mid, model):
        self.mid = mid
        self.model = model
        self.free = list(range(model.slots))
        # slot -> [episode lease id, monotonic last-use]
        self.live = {}
        # stateless: episode id -> monotonic last-use
        self.stateless_eps = {}
        # WeightBus version THIS model serves (None until its first
        # adopted snapshot) — replies are stamped per executing model,
        # so a co-hosted model the bus never updated is not reported
        # at another model's version
        self.weight_version = None


class PolicyServer:
    """One served model behind a ROUTER socket (continuous batching) or
    a REP socket (``serial=True`` — the one-request-per-exchange
    baseline ``serve_batch_x`` is measured against).

    Params
    ------
    address: str
        Endpoint to bind (``tcp://host:*`` binds an ephemeral port;
        resolved endpoint on :attr:`address`).
    model:
        A served-model adapter (:class:`LinearModel`,
        :class:`PolicyModel`, :class:`SeqFormerModel`): ``kind``,
        ``obs_dim``, ``slots`` (0 = stateless), ``pad_slot``,
        ``reset_rows(idx)``, ``step_rows(idx, obs)`` (and optionally
        ``prefill_rows(idx, prefix)``) — OR a ``{model_id: adapter}``
        dict to host several models behind one socket (**multi-model
        routing**): requests carry ``model`` in the envelope, each
        model keeps its OWN slot pool and its own jitted bucket cache,
        and a tick batches one model's requests (requests without a
        ``model`` key go to the first/default model, so a single-model
        workload against a multi-model server is byte-identical to a
        single-model server — test-locked).
    serial: bool
        REP socket, batch size 1, no queue — the serial baseline.
    tick_ms: float
        Admission window once the queue is non-empty: how long one tick
        waits for more arrivals before computing (latency it trades for
        batch occupancy).
    max_batch: int
        Largest bucket (and the most requests one tick serves).
    buckets: tuple | None
        Pad-to sizes (one XLA compilation each); default powers of two
        up to ``max_batch``.
    slot_ttl_s: float | None
        Idle-slot eviction horizon: a ``reset`` finding no free slot
        reclaims slots idle longer than this (None = never evict, the
        reset is denied instead).
    subscriber: blendjax.weights.WeightSubscriber | None
        WeightBus subscription (docs/weight_bus.md): polled from the
        serve loop — a complete, digest-verified snapshot is staged
        off-tick and hot-swapped into the hosted model **between
        ticks** (KV-cache slots, leases and in-flight exactly-once
        retries survive; a torn snapshot is discarded and the last
        good version keeps serving).  Every reply is stamped with
        ``weight_version`` once a snapshot has been adopted.
    """

    def __init__(self, address, model, *, serial=False, tick_ms=2.0,
                 max_batch=64, buckets=None, slot_ttl_s=None,
                 reply_cache_depth=REPLY_CACHE_DEPTH, counters=None,
                 timer=None, context=None, shm_base=None,
                 subscriber=None):
        import zmq

        if isinstance(model, dict):
            if not model:
                raise ValueError("multi-model server needs >= 1 model")
            self._models = {
                str(k): _ModelState(str(k), m) for k, m in model.items()
            }
        else:
            # single adapter: hosted under its kind (what a multi-model
            # dict hosting just this model would naturally be keyed by)
            self._models = {model.kind: _ModelState(model.kind, model)}
        self._default_id = next(iter(self._models))
        self.serial = bool(serial)
        self.tick_ms = float(tick_ms)
        self.buckets = tuple(sorted(
            int(b) for b in (buckets or default_buckets(int(max_batch)))
        ))
        if not self.buckets or self.buckets[0] < 1:
            raise ValueError(f"buckets must be positive: {self.buckets}")
        # the largest bucket IS the most requests one tick can pad to —
        # a max_batch beyond it would index past the padded arrays
        self.max_batch = min(int(max_batch), self.buckets[-1])
        self.slot_ttl_s = slot_ttl_s
        self.counters = counters if counters is not None else fleet_counters
        self.timer = timer if timer is not None else StageTimer()
        self._reply_cache = OrderedDict()
        self._reply_cache_depth = int(reply_cache_depth)
        self._queue = deque()
        self._pending = {}  # mid -> _Pending still queued (dedupe)
        # Slot pools live per hosted model (:class:`_ModelState`):
        # ``live`` maps slot -> [episode lease id, monotonic last-use].
        # The lease id disambiguates slot REUSE: an evicted episode's
        # client still holds the slot number, and without the lease its
        # next step would silently advance the new tenant's cache row.
        # Stateless models have no slot pool, but the admission window
        # still needs a live-episode count for its early exit (a
        # blocking client keeps one step in flight, so waiting past
        # that count is pure latency): ``stateless_eps`` maps episode
        # id -> last monotonic use, touched by reset AND step (so a
        # client that resumed past a server restart re-registers),
        # pruned after STATELESS_TTL_S idle (a crashed client must not
        # inflate the window target forever — state*ful* slots decay
        # via slot_ttl_s eviction, this is the stateless analogue).
        # The episode-lease sequence is server-GLOBAL, so no two hosted
        # models can ever hand out the same lease id.
        self._episode_seq = 0
        self._ctx = context or zmq.Context.instance()
        self._sock = self._ctx.socket(zmq.REP if self.serial
                                      else zmq.ROUTER)
        self._sock.setsockopt(zmq.LINGER, 0)
        if address.endswith(":*") or address.endswith(":0"):
            base = address.rsplit(":", 1)[0]
            port = self._sock.bind_to_random_port(base)
            self.address = f"{base}:{port}"
        else:
            self._sock.bind(address)
            self.address = address
        #: same-host ShmRPC transport (None when disabled): serves the
        #: SAME admission queue/slot pools — a request is a request
        #: whichever wire delivered it; the ZMQ socket stays the
        #: control plane and the remote-client path
        self._shm = None
        if shm_rpc.enabled():
            self._shm = shm_rpc.ShmRpcServer(
                base=shm_base or shm_rpc.new_base("ps"),
                counters=self.counters, bytes_counter="serve_shm_bytes",
                who="policy server",
            )
        self._poller = zmq.Poller()
        self._poller.register(self._sock, zmq.POLLIN)
        if self._shm is not None and self._shm.fd is not None:
            self._poller.register(self._shm.fd, zmq.POLLIN)
        #: WeightBus subscription (None = static weights) and the
        #: version every reply is stamped with after the first adopted
        #: snapshot (None until then, so a bus-less server's replies
        #: stay byte-identical to pre-bus servers)
        self.subscriber = subscriber
        self.weight_version = None
        if subscriber is not None:
            # inherit the server's telemetry sinks unless the caller
            # wired its own, and wake the serve loop for pushed chunks
            if subscriber.counters is None:
                subscriber.counters = self.counters
            if subscriber.timer is None:
                subscriber.timer = self.timer
            self._poller.register(subscriber.sock, zmq.POLLIN)

    @property
    def shm_endpoint(self):
        """The advertised ``shm://`` endpoint (None on pure-ZMQ
        servers)."""
        return self._shm.endpoint if self._shm is not None else None

    @property
    def model(self):
        """The default hosted model's adapter (the single model for
        single-model servers) — the pre-multi-model surface tests and
        benches poke at."""
        return self._models[self._default_id].model

    @property
    def models(self):
        """Hosted model ids, default first."""
        return tuple(self._models)

    def _state_or_error(self, msg):
        """Resolve the request's model state; returns ``(state, None)``
        or ``(None, error reply)`` for an unknown model id."""
        mid = msg.get("model")
        st = self._models.get(self._default_id if mid is None else mid)
        if st is None:
            return None, {"error": (
                f"unknown model {mid!r}; hosted: {sorted(self._models)}"
            )}
        return st, None

    # -- slot pool -----------------------------------------------------------

    def _alloc_slot(self, st):
        """Returns (slot, episode lease id) or (None, None) when full."""
        if st.model.slots == 0:
            self._episode_seq += 1
            st.stateless_eps[self._episode_seq] = time.monotonic()
            return -1, self._episode_seq
        if not st.free and self.slot_ttl_s is not None:
            now = time.monotonic()
            stale = [s for s, (_, ts) in st.live.items()
                     if now - ts > self.slot_ttl_s]
            for s in stale:
                del st.live[s]
                st.free.append(s)
            if stale:
                self.counters.incr("serve_evictions", len(stale))
        if not st.free:
            return None, None
        slot = st.free.pop()
        self._episode_seq += 1
        st.live[slot] = [self._episode_seq, time.monotonic()]
        st.model.reset_rows(np.asarray([slot]))
        return slot, self._episode_seq

    def _free_slot(self, st, slot, episode=None):
        lease = st.live.get(slot)
        if lease is None:
            return False
        if episode is not None and lease[0] != episode:
            return False  # a stale close must not kill the new tenant
        del st.live[slot]
        st.free.append(slot)
        return True

    # -- request handling ----------------------------------------------------

    def _live_episodes(self):
        """Live episodes across every hosted model (window targeting,
        stats, the gateway's load scrape)."""
        return sum(
            len(st.live) if st.model.slots > 0 else len(st.stateless_eps)
            for st in self._models.values()
        )

    def _cmd_hello(self, msg):
        st = self._models[self._default_id]
        return {
            "model": st.model.kind,
            "obs_dim": st.model.obs_dim,
            "slots": st.model.slots,
            "free_slots": len(st.free),
            "serial": self.serial,
            "int8": bool(getattr(st.model, "int8", False)),
            "max_batch": self.max_batch,
            "buckets": list(self.buckets),
            "models": {
                s.mid: {
                    "kind": s.model.kind,
                    "obs_dim": s.model.obs_dim,
                    "slots": s.model.slots,
                    "free_slots": len(s.free),
                    "int8": bool(getattr(s.model, "int8", False)),
                }
                for s in self._models.values()
            },
            "shm": self._shm.info() if self._shm is not None else None,
            "pid": os.getpid(),
        }

    def _cmd_reset(self, msg):
        st, err = self._state_or_error(msg)
        if err is not None:
            return err
        slot, episode = self._alloc_slot(st)
        if slot is None:
            self.counters.incr("serve_slot_denied")
            return {"error": (
                f"no free episode slot ({st.model.slots} live on model "
                f"{st.mid!r}); close an episode or raise slots="
            )}
        reply = {"slot": slot, "episode": episode}
        prefix = msg.get("prefix")
        if prefix is not None:
            err = self._prefill(st, slot, episode, prefix, reply)
            if err is not None:
                return err
        self.counters.incr("serve_resets")
        return reply

    def _prefill(self, st, slot, episode, prefix, reply):
        """Batched prefill admission: replay a T-step observation
        prefix into the freshly-allocated slot with ONE teacher-forced
        pass (``model.prefill_rows``) instead of T serial decode steps.
        Mutates ``reply`` in place on success; returns an error reply
        (with the slot freed again) on failure."""
        def fail(text):
            if st.model.slots > 0:
                self._free_slot(st, slot, episode)
            else:
                st.stateless_eps.pop(episode, None)
            return {"error": text}

        if not hasattr(st.model, "prefill_rows") or st.model.slots == 0:
            return fail(
                f"model {st.mid!r} ({st.model.kind}) is stateless or "
                "has no prefill path: admit without a prefix"
            )
        try:
            prefix = np.asarray(prefix, np.float32)
        except (TypeError, ValueError) as exc:
            return fail(f"prefix not coercible to float32: {exc}")
        if prefix.ndim != 2 or prefix.shape[0] < 1 \
                or prefix.shape[1] != st.model.obs_dim:
            return fail(
                f"prefix shape {prefix.shape} != (T >= 1, "
                f"{st.model.obs_dim})"
            )
        try:
            pred = st.model.prefill_rows(np.asarray([slot]), prefix)
        except Exception as exc:  # noqa: BLE001 - surfaced to client
            logger.exception("policy server: prefill failed")
            return fail(f"prefill failed: {type(exc).__name__}: {exc}")
        self.counters.incr("serve_prefills")
        # the prediction for position T (what the T'th serial step
        # would have returned) and the position the next step consumes
        reply["pred"] = np.ascontiguousarray(pred)
        reply["pos"] = int(prefix.shape[0])
        return None

    def _cmd_close(self, msg):
        st, err = self._state_or_error(msg)
        if err is not None:
            return err
        if st.model.slots == 0:
            closed = st.stateless_eps.pop(
                msg.get("episode"), None
            ) is not None
        else:
            closed = self._free_slot(st, int(msg.get("slot", -1)),
                                     msg.get("episode"))
        if closed:
            # a no-op close (unknown slot, stale/pruned lease, a
            # restarted server) is answered but not counted:
            # serve_resets vs serve_closes must reconcile
            self.counters.incr("serve_closes")
        return {"closed": closed}

    def _cmd_stats(self, msg):
        # top-level slot fields describe the DEFAULT model (the whole
        # server for single-model hosting, where slots/free/live stay
        # mutually consistent); per-model occupancy lives under
        # ``per_model`` so multi-model capacity math has coherent
        # numbers instead of a cross-model mix
        st = self._models[self._default_id]
        return {
            "model": st.model.kind,
            "slots": st.model.slots,
            "live_slots": len(st.live),
            "live_episodes": (
                len(st.live) if st.model.slots > 0
                else len(st.stateless_eps)
            ),
            "free_slots": len(st.free),
            "queued": len(self._queue),
            "serial": self.serial,
            "models": list(self._models),
            "weight_version": self.weight_version,
            "per_model": {
                s.mid: {
                    "slots": s.model.slots,
                    "free_slots": len(s.free),
                    "live_slots": len(s.live),
                    "live_episodes": (
                        len(s.live) if s.model.slots > 0
                        else len(s.stateless_eps)
                    ),
                }
                for s in self._models.values()
            },
            "counters": self.counters.snapshot(),
            "pid": os.getpid(),
        }

    def _cmd_telemetry(self, msg):
        """This process's telemetry in the TelemetryHub merge shape —
        the PULL half of remote scraping (a consumer-side hub registers
        ``lambda: client.telemetry()`` and this server needs no
        exporter, no extra socket).  ``queued``/``live_episodes``/
        ``models``/``hello`` ride along for the gateway's cached load
        scrape — one RPC covers liveness, load, capability AND
        telemetry (the gateway's own ``hello`` reply merges the
        capability fields so PR-10 hello consumers work unchanged
        against a gateway address)."""
        st = self._models[self._default_id]
        return {
            "model": st.model.kind,
            "models": list(self._models),
            "queued": len(self._queue),
            "live_episodes": self._live_episodes(),
            # the gateway's canary router learns per-replica versions
            # from this field on its cached scrape (docs/weight_bus.md)
            "weight_version": self.weight_version,
            "hello": {
                "model": st.model.kind,
                "obs_dim": st.model.obs_dim,
                "slots": st.model.slots,
                "serial": self.serial,
                "int8": bool(getattr(st.model, "int8", False)),
                "max_batch": self.max_batch,
                "buckets": list(self.buckets),
            },
            "pid": os.getpid(),
            "counters": self.counters.snapshot(),
            "stages": self.timer.snapshot_serialized(),
        }

    def _control_reply(self, msg):
        cmd = msg.get("cmd")
        handler = getattr(self, f"_cmd_{cmd}", None)
        if handler is None:
            reply = {"error": f"unknown serve command {cmd!r}"}
        else:
            try:
                reply = handler(msg)
            except Exception as exc:  # noqa: BLE001 - surfaced to client
                logger.exception("policy server: %r failed", cmd)
                reply = {"error": f"{type(exc).__name__}: {exc}"}
        if "error" in reply:
            self.counters.incr("serve_errors")
        return reply

    def _poll_weights(self):
        """Drain the WeightBus subscription and hot-swap a staged
        snapshot — called from the serve loop BETWEEN ticks, the one
        point where no batch is in flight, so slots/leases/reply-cache
        state cannot be half-stepped under a swap.  A snapshot the
        model refuses (structure/shape drift) is discarded and counted;
        the last good version keeps serving either way."""
        if self.subscriber is None:
            return
        snap = self.subscriber.poll()
        if snap is None:
            return
        # routing: the snapshot's own model id wins; a publisher that
        # does not stamp one (a learner publishing its only model)
        # targets the model the SUBSCRIBER was attached for, default
        # model last
        target = (snap.model if snap.model is not None
                  else self.subscriber.model
                  if self.subscriber.model is not None
                  else self._default_id)
        st = self._models.get(target)
        t0 = time.perf_counter()
        try:
            if st is None:
                raise KeyError(
                    f"snapshot for unhosted model {target!r} "
                    f"(hosted: {sorted(self._models)})"
                )
            st.model.apply_weights(snap.tree())
        except Exception as exc:  # noqa: BLE001 - keep serving last good
            self.counters.incr("weight_apply_failed")
            logger.warning(
                "policy server: weight snapshot v%d refused (%s: %s); "
                "still serving v%s", snap.version, type(exc).__name__,
                exc, self.weight_version,
            )
            return
        st.weight_version = snap.version
        # the server-level scalar (telemetry/stats — what the gateway
        # scrapes a replica's rollout progress from) tracks the latest
        # adopted snapshot; per-reply stamps come from the EXECUTING
        # model's own version in _finish
        self.weight_version = snap.version
        self.counters.incr("weight_adopted")
        self.timer.add("weight_swap", time.perf_counter() - t0)
        logger.info("policy server: weights v%d hot-swapped (step %d)",
                    snap.version, snap.step)

    def _finish(self, ident, msg, reply, *, span_name, t0_us,
                ding=True):
        """Stamp correlation id + span + weight version, cache mutating
        replies, send.  ``ding=False`` defers the shm doorbell to the
        caller's burst flush (the batched multi-record wake)."""
        st = self._models.get(msg.get("model") or self._default_id)
        if st is not None and st.weight_version is not None:
            # the EXECUTING model's version (a co-hosted model the bus
            # never updated stays unstamped rather than riding another
            # model's version), stamped BEFORE the reply cache below,
            # so a retry answered from the cache reports the version
            # that actually executed it — not the version serving at
            # retry time
            reply["weight_version"] = st.weight_version
        mid = msg.get(wire.BTMID_KEY)
        span_ctx = msg.get(wire.SPAN_KEY)
        if isinstance(span_ctx, dict) and span_ctx.get("trace") is not None:
            reply = dict(reply)
            reply[wire.SPANS_KEY] = [make_span(
                span_name, t0_us, trace=span_ctx["trace"], cat="serve",
            )]
        if mid is not None:
            reply[wire.BTMID_KEY] = mid
            if msg.get("cmd") in MUTATING_CMDS:
                self._reply_cache[mid] = reply
                while len(self._reply_cache) > self._reply_cache_depth:
                    self._reply_cache.popitem(last=False)
        self._send(ident, reply, ding=ding)

    def _shm_gather_send(self, chan, reply, ding=True):
        """Gather-into-ring reply: reserve the ring record up front and
        land the reply's array leaves DIRECTLY in it (``begin_send``
        views) instead of staging them through ``encode`` + the
        ``send_frames`` memcpy — the replay shard's zero-copy reply
        discipline on the serve reply path.  False defers to the
        generic send (array-less reply, ring full/oversized, old
        native layer)."""
        bufs = []
        header = wire.strip_arrays(reply, bufs)
        if not bufs:
            return False
        head_bytes = wire.dumps(header)
        sizes = [len(head_bytes)] + [b.nbytes for b in bufs]
        views = self._shm.begin_send(chan, sizes)
        if views is None:
            return False
        done = False
        try:
            views[0][:] = np.frombuffer(head_bytes, np.uint8)
            for b, dst in zip(bufs, views[1:]):
                if b.nbytes:
                    dst[:] = b.view(np.uint8).reshape(-1)
            done = True
        finally:
            if not done:
                # a torn record with an intact header would decode as
                # WRONG data — poison the header so the client drops
                # the record (its same-mid retry re-fetches from the
                # reply cache), then publish: the reservation must
                # never dangle
                views[0][: min(8, len(head_bytes))] = 0
            try:
                self._shm.commit_send(chan, ding=ding)
            except OSError:
                pass  # channel died mid-reply: the retry re-fetches
        return True

    def _send(self, ident, reply, ding=True):
        import zmq

        if ident is not None and getattr(ident, "shm_channel", False):
            # the request arrived over shm: the reply goes back down
            # the same channel (a dead/full channel is dropped — the
            # client demotes to ZMQ and its same-mid retry re-fetches
            # from the reply cache)
            if self._shm is not None and (
                self._shm_gather_send(ident, reply, ding=ding)
                or self._shm.send(ident, reply, raw_buffers=True,
                                  ding=ding)
            ):
                self.counters.incr("serve_replies")
            return
        try:
            if self.serial:
                sent = wire.send_message(self._sock, reply,
                                         raw_buffers=True)
            else:
                sent = wire.send_message_router(self._sock, ident, reply,
                                                raw_buffers=True)
            self.counters.incr("serve_wire_bytes", sent)
            self.counters.incr("serve_replies")
        except zmq.ZMQError:
            pass  # client gone; its retry will re-dial

    def _admit(self, ident, msg):
        """One decoded request: answer control commands immediately,
        queue ``step``s for the next tick, dedupe retries."""
        self.counters.incr("serve_requests")
        mid = msg.get(wire.BTMID_KEY)
        cmd = msg.get("cmd")
        t0_us = now_us()
        if mid is not None and cmd in MUTATING_CMDS \
                and mid in self._reply_cache:
            # retry of a request already executed: exactly-once — the
            # cached reply answers it, nothing re-runs
            self.counters.incr("serve_cache_hits")
            self._send(ident, self._reply_cache[mid])
            return
        if cmd != "step":
            reply = self._control_reply(msg)
            self._finish(ident, msg, reply, span_name=f"serve:{cmd}",
                         t0_us=t0_us)
            return
        if mid is not None and mid in self._pending:
            # retry of a request still QUEUED: the original's reply
            # will answer it — re-point the route and drop the dup
            self.counters.incr("serve_dup_inflight")
            self._pending[mid].ident = ident
            return
        st, err = self._state_or_error(msg)
        if err is not None:
            self.counters.incr("serve_errors")
            self._finish(ident, msg, err, span_name="serve:step",
                         t0_us=t0_us)
            return
        span_ctx = msg.get(wire.SPAN_KEY)
        trace = (span_ctx or {}).get("trace") \
            if isinstance(span_ctx, dict) else None
        ent = _Pending(ident, mid, msg, trace, t0_us, st)
        self._queue.append(ent)
        if mid is not None:
            self._pending[mid] = ent

    def _step_entry_error(self, ent, text, lease=None):
        """Error-reply one queued step.  ``lease`` ("unknown"/"stale")
        rides as a structured field so a gateway can drop its own lease
        entry without parsing error prose."""
        self.counters.incr("serve_errors")
        reply = {"error": text}
        if lease is not None:
            reply["lease"] = lease
        self._finish(ent.ident, ent.msg, reply,
                     span_name="serve:step", t0_us=ent.t0_us)

    def _tick(self):
        """Drain up to ``max_batch`` queued steps into one padded,
        bucketed model call and scatter the replies.  A tick serves ONE
        hosted model (the queue head's); entries for other models are
        left in order and the return value says so, so the serve loop
        ticks again immediately instead of making them wait out another
        admission window."""
        t_assemble = time.perf_counter()
        head = None
        skipped = deque()
        batch = []
        while self._queue and len(batch) < self.max_batch:
            ent = self._queue.popleft()
            if head is None:
                head = ent.mstate
            elif ent.mstate is not head:
                skipped.append(ent)
                continue
            if ent.mid is not None:
                self._pending.pop(ent.mid, None)
            st = ent.mstate
            stateful = st.model.slots > 0
            slot = int(ent.msg.get("slot", -1)) if stateful else -1
            if not stateful:
                ep = ent.msg.get("episode")
                if ep is not None:
                    # touch (or re-register, after a server restart)
                    # the episode's liveness for window targeting —
                    # stateless steps are never refused
                    st.stateless_eps[ep] = time.monotonic()
            if stateful:
                lease = st.live.get(slot)
                if lease is None:
                    self._step_entry_error(ent, (
                        f"unknown episode slot {slot} (closed, evicted, "
                        "or a restarted server): reset() and resume"
                    ), lease="unknown")
                    continue
                if ent.msg.get("episode") not in (None, lease[0]):
                    # slot number reused by a NEW episode: the stale
                    # client must not advance the new tenant's cache
                    self._step_entry_error(ent, (
                        f"stale episode lease for slot {slot} (evicted "
                        "and reassigned): reset() and resume"
                    ), lease="stale")
                    continue
            try:
                obs = np.asarray(ent.msg.get("obs"), np.float32)
            except (TypeError, ValueError) as exc:
                self._step_entry_error(
                    ent, f"step obs not coercible to float32: {exc}"
                )
                continue
            if obs.shape != (head.model.obs_dim,):
                self._step_entry_error(ent, (
                    f"step obs shape {obs.shape} != "
                    f"({head.model.obs_dim},)"
                ))
                continue
            batch.append((ent, slot, obs))
        # skipped other-model entries return to the FRONT in order:
        # they are older than anything still queued behind them —
        # ``more`` asks the serve loop to tick again NOW for them
        # (same-model overflow keeps the admission-window pacing)
        more = bool(skipped)
        while skipped:
            self._queue.appendleft(skipped.pop())
        if not batch:
            return more
        model = head.model
        stateful = model.slots > 0
        n = len(batch)
        bucket = next((b for b in self.buckets if b >= n),
                      self.buckets[-1])
        for ent, _, _ in batch:
            self.timer.add("queue_wait", t_assemble - ent.t_enq)
        idx = np.full(bucket, model.pad_slot, np.int64)
        obs_arr = np.zeros((bucket, model.obs_dim), np.float32)
        pos_before = []
        now = time.monotonic()
        for j, (ent, slot, obs) in enumerate(batch):
            idx[j] = slot if stateful else j
            obs_arr[j] = obs
            if stateful:
                head.live[slot][1] = now
            pos_before.append(
                int(model.pos[slot])
                if hasattr(model, "pos") and stateful else None
            )
        t_compute = time.perf_counter()
        self.timer.add("batch_assemble", t_compute - t_assemble)
        try:
            preds = model.step_rows(idx, obs_arr)
        except Exception as exc:  # noqa: BLE001 - server must survive
            logger.exception("policy server: batched step failed")
            for ent, _, _ in batch:
                self._step_entry_error(
                    ent, f"batched step failed: {type(exc).__name__}: "
                         f"{exc}"
                )
            return more
        t_reply = time.perf_counter()
        self.timer.add("compute", t_reply - t_compute)
        self.counters.incr("serve_batches")
        if bucket > n:
            self.counters.incr("serve_batch_pad", bucket - n)
        for j, (ent, slot, _) in enumerate(batch):
            reply = {"pred": np.ascontiguousarray(preds[j])}
            if pos_before[j] is not None:
                reply["pos"] = pos_before[j]
            # deferred doorbells: the whole batch's shm replies ride
            # ONE wake per channel (flushed below), not one ding per
            # record
            self._finish(ent.ident, ent.msg, reply,
                         span_name="serve:step", t0_us=ent.t0_us,
                         ding=False)
        if self._shm is not None:
            self._shm.flush_bells()
        self.timer.add("reply", time.perf_counter() - t_reply)
        return more

    # -- serving -------------------------------------------------------------

    def _window_target(self):
        """Queue occupancy at which an admission window stops waiting:
        every live episode (a blocking client keeps at most one step in
        flight, so a fuller window cannot form), capped at the largest
        bucket.  Stateless episodes are tracked by last use and pruned
        after :data:`STATELESS_TTL_S` idle; the ``max(1, ...)`` keeps a
        client that never reset servable instead of deadlocking the
        window."""
        live = 0
        for st in self._models.values():
            if st.model.slots > 0:
                live += len(st.live)
            else:
                if st.stateless_eps:
                    cutoff = time.monotonic() - STATELESS_TTL_S
                    for ep, ts in list(st.stateless_eps.items()):
                        if ts < cutoff:
                            del st.stateless_eps[ep]
                live += len(st.stateless_eps)
        return min(self.max_batch, max(1, live))

    def _drain(self):
        """Admit every request currently sitting on the socket."""
        import zmq

        def handle(out):
            ident, msg, nbytes = out
            self.counters.incr("serve_wire_bytes", nbytes)
            reply = shm_rpc.control_reply(self._shm, msg)
            if reply is not None:
                # transport negotiation, not workload: answered outside
                # the request/reply counters and the reply cache
                try:
                    wire.send_message_router(self._sock, ident, reply)
                except zmq.ZMQError:
                    pass
                return
            self._admit(ident, msg)

        drain_socket(
            lambda: wire.recv_message_router_sized(self._sock,
                                                   flags=zmq.NOBLOCK),
            handle,
            self.counters, "policy server", "request",
        )

    def _handle_shm_msg(self, chan, msg):
        reply = shm_rpc.control_reply(self._shm, msg)
        if reply is not None:
            self._shm.send(chan, reply)
            return
        self._admit(chan, msg)
        if self.serial:
            # serial semantics are per-REQUEST (the batching baseline):
            # tick immediately so co-pumped shm requests never batch
            while self._queue:
                self._tick()

    def _drain_shm(self):
        """Admit every request pending on the shm channels (the channel
        object rides as the request's reply ident)."""
        if self._shm is not None:
            self._shm.pump(self._handle_shm_msg)

    def serve_forever(self, stop_event=None, poll_ms=50):
        import zmq

        if self.serial:
            self._serve_serial(stop_event, poll_ms)
            return
        while stop_event is None or not stop_event.is_set():
            try:
                # between ticks: the hot-swap point (no batch in
                # flight, every queued entry still un-executed)
                self._poll_weights()
                if not self._queue:
                    self._poller.poll(poll_ms)
                    self._drain()
                    self._drain_shm()
                    if not self._queue:
                        continue
                # admission window: work is queued — wait up to tick_ms
                # for co-arriving requests (the latency the scheduler
                # trades for occupancy).  Leave early on the first
                # empty poll slice, a full bucket, or once every LIVE
                # episode has a step queued (episodes step one request
                # at a time, so nobody else can arrive — waiting out
                # the window would be pure latency)
                t_end = time.perf_counter() + self.tick_ms / 1000.0
                while len(self._queue) < self._window_target():
                    rem_ms = (t_end - time.perf_counter()) * 1e3
                    if rem_ms <= 0:
                        break
                    if not self._poller.poll(max(1, int(rem_ms))):
                        break  # window elapsed with nothing new
                    self._drain()
                    self._drain_shm()
            except zmq.ZMQError:
                return  # socket closed under us: clean shutdown
            if self._queue:
                # a tick serves one model; entries it skipped for model
                # mismatch are served by immediate follow-up ticks, not
                # parked behind another admission window
                while self._tick():
                    pass

    def _serve_serial(self, stop_event, poll_ms):
        """The REP baseline: one request, one (batch-1) reply.  shm
        channels are served from the same loop (their replies ride
        their own rings, so the REP alternation only governs the ZMQ
        socket)."""
        import zmq

        while stop_event is None or not stop_event.is_set():
            try:
                events = dict(self._poller.poll(poll_ms))
                self._poll_weights()  # between (batch-1) ticks
                self._drain_shm()  # ticks per message (serial handler)
                if self._sock not in events:
                    continue
                try:
                    msg, nbytes = wire.recv_message_sized(self._sock)
                    self.counters.incr("serve_wire_bytes", nbytes)
                except zmq.ZMQError:
                    return
                except Exception as exc:  # noqa: BLE001 - see _drain
                    # REP alternation: the garbled request was consumed,
                    # so a reply is owed before the next recv (_send
                    # keeps the serve_replies count honest)
                    self.counters.incr("serve_errors")
                    logger.warning(
                        "policy server: undecodable request (%s: %s)",
                        type(exc).__name__, exc,
                    )
                    self._send(None, {
                        "error": "undecodable request (corrupt frames)"
                    })
                    continue
            except zmq.ZMQError:
                return
            reply = shm_rpc.control_reply(self._shm, msg)
            if reply is not None:
                try:
                    wire.send_message(self._sock, reply)
                except zmq.ZMQError:
                    return
                continue
            self._admit(None, msg)
            while self._queue:
                self._tick()

    def close(self):
        try:
            self._sock.close(0)
        except Exception:  # noqa: BLE001 - shutdown best-effort
            pass
        if self.subscriber is not None:
            try:
                self.subscriber.close()
            except Exception:  # noqa: BLE001
                pass
        if self._shm is not None:
            try:
                self._shm.close(unlink=True)
            except Exception:  # noqa: BLE001
                pass
            self._shm = None


# ---------------------------------------------------------------------------
# in-process and supervised-process surfaces
# ---------------------------------------------------------------------------


class _LocalServerHandle:
    """An in-process server (thread) for tests and benchmarks."""

    def __init__(self, server, thread, stop):
        self.server = server
        self.address = server.address
        self._thread = thread
        self._stop = stop

    def close(self):
        self._stop.set()
        self._thread.join(timeout=5)
        self.server.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


def start_server_thread(model, *, address="tcp://127.0.0.1:*",
                        serial=False, counters=None, timer=None,
                        **kwargs):
    """Serve a :class:`PolicyServer` from a daemon thread; returns a
    handle with ``.address``, ``.server`` and ``.close()``."""
    server = PolicyServer(
        address, model, serial=serial, counters=counters, timer=timer,
        **kwargs,
    )
    stop = threading.Event()
    thread = threading.Thread(
        target=server.serve_forever, kwargs={"stop_event": stop},
        daemon=True, name="bjx-policy-server",
    )
    thread.start()
    return _LocalServerHandle(server, thread, stop)


class _ServeLaunchInfo:
    """Duck-typed ``launch_info`` so :class:`~blendjax.btt.watchdog.
    FleetWatchdog` supervises the server process exactly like Blender
    producers or replay shards."""

    def __init__(self, processes, addresses):
        self.processes = processes
        self.addresses = {"SERVE": addresses}


class ServerProcess:
    """One policy-server *process* with a launcher-compatible surface
    (``launch_info`` + ``respawn(idx)``) so ``FleetWatchdog(restart=
    True)`` respawns it after a SIGKILL with its original command line.
    Model state is rebuilt deterministically from ``--seed`` — episode
    slots are fresh, which is exactly the contract clients see: a step
    against a restarted server errors (unknown slot) and the client
    resumes with ``reset()``."""

    def __init__(self, *, model="linear", address=None, seed=0,
                 obs_dim=8, slots=16, length=64, window=None,
                 num_actions=4, int8=False, serial=False, tick_ms=2.0,
                 max_batch=64, work_us=0, subscribe=None, python=None,
                 ready_timeout=60.0, extra_args=()):
        from blendjax.replay.shard_client import free_port

        self.address = address or f"tcp://127.0.0.1:{free_port()}"
        self.python = python or sys.executable
        self.ready_timeout = ready_timeout
        #: the server's /dev/shm prefix, allocated HERE (the parent) so
        #: teardown and the watchdog respawn path can sweep whatever a
        #: SIGKILLed server (and its clients) left behind
        self.shm_base = shm_rpc.new_base("sp") if shm_rpc.enabled() \
            else None
        self._cmd = [
            self.python, "-m", "blendjax.serve.server",
            "--address", self.address,
            "--model", model,
            "--seed", str(seed),
            "--obs-dim", str(obs_dim),
            "--slots", str(slots),
            "--length", str(length),
            "--num-actions", str(num_actions),
            "--tick-ms", str(tick_ms),
            "--max-batch", str(max_batch),
        ]
        if self.shm_base is not None:
            self._cmd += ["--shm-base", self.shm_base]
        if work_us:
            self._cmd += ["--work-us", str(work_us)]
        if subscribe:
            self._cmd += ["--subscribe", subscribe]
        if window is not None:
            self._cmd += ["--window", str(window)]
        if int8:
            self._cmd.append("--int8")
        if serial:
            self._cmd.append("--serial")
        self._cmd += list(extra_args)
        self.launch_info = None

    def _spawn(self):
        # one child-environment policy for the whole repo (launcher,
        # shard fleet, serve server): child_env prepends the repo root
        # to PYTHONPATH
        from blendjax.btt.launcher import child_env

        env = child_env()
        # jax models pin to CPU in the child; a dead TPU tunnel relay
        # must not hang server startup (same rationale as conftest)
        env.setdefault("JAX_PLATFORMS", "cpu")
        env.pop("PALLAS_AXON_POOL_IPS", None)
        return subprocess.Popen(self._cmd, env=env,
                                start_new_session=True)

    def __enter__(self):
        self.launch_info = _ServeLaunchInfo([self._spawn()],
                                            [self.address])
        try:
            self.wait_ready(self.ready_timeout)
        except BaseException:
            self.close()
            raise
        return self

    def wait_ready(self, timeout=60.0):
        from blendjax.serve.client import ServeClient

        deadline = time.monotonic() + timeout
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise TimeoutError(
                    f"policy server at {self.address} not ready within "
                    f"{timeout:.1f}s"
                )
            client = ServeClient(self.address, timeoutms=500)
            try:
                client.hello(timeout_ms=500)
                return
            except TimeoutError:
                continue
            finally:
                client.close()

    def respawn(self, idx=0):
        """Relaunch with the original command line (the watchdog's
        contract).  The dead incarnation's ``/dev/shm`` objects are
        swept first — a SIGKILL runs no cleanup."""
        if self.shm_base is not None:
            shm_rpc.unlink_base(self.shm_base)
        proc = self._spawn()
        self.launch_info.processes[idx] = proc
        return proc

    def close(self):
        info = self.launch_info
        if info is None:
            return
        for p in info.processes:
            try:
                p.terminate()
            except Exception:  # noqa: BLE001
                pass
        for p in info.processes:
            try:
                p.wait(timeout=5)
            except Exception:  # noqa: BLE001
                try:
                    p.kill()
                except Exception:  # noqa: BLE001
                    pass
        if self.shm_base is not None:
            shm_rpc.unlink_base(self.shm_base)

    def __exit__(self, *exc):
        self.close()
        return False


class ServerFleet:
    """N policy-server replica *processes* behind ONE launcher-
    compatible surface (a ``launch_info`` spanning every replica +
    ``respawn(idx)``), so a single :class:`~blendjax.btt.watchdog.
    FleetWatchdog` supervises the whole serve fleet — the
    :class:`~blendjax.serve.gateway.ServeGateway`'s supervision story
    (docs/serving.md).  All replicas share one ``seed`` by default, so
    every replica serves identical weights (what lease failover needs:
    after a ``reset()`` any healthy replica continues the workload);
    pass ``seeds=`` to vary them."""

    def __init__(self, replicas, *, seed=0, seeds=None, **kwargs):
        if seeds is not None and len(seeds) != replicas:
            raise ValueError(
                f"seeds has {len(seeds)} entries for {replicas} replicas"
            )
        # kept for grow(): newcomers are spawned with the same config
        # (and the shared seed, so they serve identical weights)
        self._seed = seed
        self._kwargs = dict(kwargs)
        self._procs = [
            ServerProcess(seed=(seeds[i] if seeds is not None else seed),
                          **kwargs)
            for i in range(int(replicas))
        ]
        self.launch_info = None

    @property
    def addresses(self):
        return [None if p is None else p.address for p in self._procs]

    def __enter__(self):
        try:
            # spawn every replica first, then wait: startup overlaps
            for p in self._procs:
                p.launch_info = _ServeLaunchInfo([p._spawn()],
                                                 [p.address])
            for p in self._procs:
                p.wait_ready(p.ready_timeout)
        except BaseException:
            self.close()
            raise
        self.launch_info = _ServeLaunchInfo(
            [p.launch_info.processes[0] for p in self._procs],
            self.addresses,
        )
        return self

    def respawn(self, idx):
        """Relaunch replica ``idx`` with its original command line (the
        watchdog's contract)."""
        if self._procs[idx] is None:
            raise RuntimeError(
                f"replica {idx} is retired; a retired slot is never "
                "respawned (grow() to add capacity)"
            )
        proc = self._procs[idx].respawn(0)
        self.launch_info.processes[idx] = proc
        return proc

    def grow(self, n=1, *, seeds=None):
        """Spawn ``n`` NEW replicas into the live fleet (autoscale
        scale-up).  They are appended — existing fleet indices (and so
        the gateway's ``r<idx>`` id alignment and any watchdog watching
        ``launch_info``) never move.  Spawns overlap, then each
        newcomer is waited ready.  Returns ``[(idx, address), ...]``
        for the gateway admission."""
        if self.launch_info is None:
            raise RuntimeError("grow() needs an entered fleet")
        if seeds is not None and len(seeds) != int(n):
            raise ValueError(
                f"seeds has {len(seeds)} entries for {n} new replicas"
            )
        added = []
        for j in range(int(n)):
            p = ServerProcess(
                seed=(seeds[j] if seeds is not None else self._seed),
                **self._kwargs,
            )
            self._procs.append(p)
            idx = len(self._procs) - 1
            p.launch_info = _ServeLaunchInfo([p._spawn()], [p.address])
            self.launch_info.processes.append(
                p.launch_info.processes[0])
            self.launch_info.addresses["SERVE"].append(p.address)
            added.append((idx, p.address))
        try:
            for idx, _ in added:
                self._procs[idx].wait_ready(self._procs[idx].ready_timeout)
        except BaseException:
            # a newcomer that never came up is retired on the spot: the
            # established fleet is untouched and indices stay stable
            for idx, _ in added:
                self.retire(idx)
            raise
        return added

    def retire(self, idx):
        """Retire replica ``idx`` permanently (autoscale scale-down,
        AFTER its gateway drain reached zero leases): terminate the
        process and sweep its ``/dev/shm``.  The index slot is kept
        (``None``) so fleet indices stay aligned with gateway ids and
        the watchdog skips it instead of respawning it.  Idempotent."""
        p = self._procs[idx]
        if p is None:
            return False
        # slot goes None BEFORE the kill: a watchdog polling between
        # the two must see a retired slot, not a death to respawn
        self._procs[idx] = None
        if self.launch_info is not None:
            self.launch_info.processes[idx] = None
        p.close()
        return True

    def shrink(self, victims):
        """Retire every index in ``victims``; returns those actually
        retired (already-retired slots are skipped)."""
        return [idx for idx in victims if self.retire(idx)]

    def close(self):
        for p in self._procs:
            if p is not None:
                p.close()
        self.launch_info = None

    def __exit__(self, *exc):
        self.close()
        return False


# ---------------------------------------------------------------------------
# process entry point
# ---------------------------------------------------------------------------


def build_model(args, kind=None, seed=None):
    """Deterministic model construction from CLI args (seeded init —
    what makes a respawned server byte-identical to its predecessor).
    ``kind``/``seed`` override the args' own (the ``--extra-model``
    path builds secondary hosted models through the same code)."""
    if kind is not None or seed is not None:
        args = argparse.Namespace(**{
            **vars(args),
            "model": kind if kind is not None else args.model,
            "seed": seed if seed is not None else args.seed,
        })
    if args.model == "linear":
        return LinearModel(obs_dim=args.obs_dim, slots=args.slots,
                           seed=args.seed,
                           work_us=getattr(args, "work_us", 0))
    import jax

    key = jax.random.PRNGKey(args.seed)
    if args.model == "policy":
        from blendjax.models import policy

        params = policy.init(key, args.obs_dim, args.num_actions)
        return PolicyModel(params, args.obs_dim, int8=args.int8)
    if args.model == "seqformer":
        from blendjax.models import seqformer

        params = seqformer.init(
            key, obs_dim=args.obs_dim, d_model=args.d_model,
            n_heads=args.n_heads, n_layers=args.n_layers,
            max_len=max(args.length, 8),
        )
        return SeqFormerModel(
            params, args.slots, args.length, window=args.window,
            int8=args.int8,
        )
    raise ValueError(f"unknown --model {args.model!r}")


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="Serve one blendjax policy/world-model."
    )
    ap.add_argument("--address", required=True)
    ap.add_argument("--model", default="linear",
                    choices=("linear", "policy", "seqformer"))
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--obs-dim", type=int, default=8)
    ap.add_argument("--num-actions", type=int, default=4)
    ap.add_argument("--slots", type=int, default=16)
    ap.add_argument("--length", type=int, default=64)
    ap.add_argument("--window", type=int, default=None)
    ap.add_argument("--d-model", type=int, default=32)
    ap.add_argument("--n-heads", type=int, default=4)
    ap.add_argument("--n-layers", type=int, default=2)
    ap.add_argument("--int8", action="store_true")
    ap.add_argument("--serial", action="store_true")
    ap.add_argument("--tick-ms", type=float, default=2.0)
    ap.add_argument("--max-batch", type=int, default=64)
    ap.add_argument("--work-us", type=float, default=0,
                    help="linear model only: sleep-based per-row "
                         "compute stand-in (gateway scale-out bench)")
    ap.add_argument("--subscribe", default=None,
                    help="WeightBus publisher address to subscribe to "
                         "(docs/weight_bus.md): published snapshots "
                         "hot-swap into the served model between ticks")
    ap.add_argument("--shm-base", default=None,
                    help="/dev/shm name prefix for the ShmRPC transport "
                         "(supervising parents pass one so they can "
                         "sweep a SIGKILLed server's objects)")
    ap.add_argument(
        "--extra-model", action="append", default=[],
        metavar="NAME=KIND",
        help="host an additional model under NAME (multi-model "
             "routing); the i'th extra model inits from seed+1+i, so a "
             "respawned server rebuilds every hosted model "
             "deterministically from the one command line",
    )
    args = ap.parse_args(argv)
    logging.basicConfig(level=logging.INFO)
    model = build_model(args)
    if args.extra_model:
        models = {model.kind: model}
        for i, spec in enumerate(args.extra_model):
            name, sep, kind = spec.partition("=")
            if not sep or not name or not kind:
                ap.error(f"--extra-model needs NAME=KIND, got {spec!r}")
            if name in models:
                ap.error(f"duplicate hosted model name {name!r}")
            models[name] = build_model(args, kind=kind,
                                       seed=args.seed + 1 + i)
        model = models
    subscriber = None
    if args.subscribe:
        from blendjax.weights.bus import WeightSubscriber

        subscriber = WeightSubscriber(args.subscribe)
    server = PolicyServer(
        args.address, model, serial=args.serial,
        tick_ms=args.tick_ms, max_batch=args.max_batch,
        shm_base=args.shm_base, subscriber=subscriber,
    )
    stop = threading.Event()

    def _term(signum, frame):
        stop.set()

    signal.signal(signal.SIGTERM, _term)
    signal.signal(signal.SIGINT, _term)
    logger.info(
        "policy server (%s%s) serving %s", args.model,
        ", int8" if args.int8 else "", server.address,
    )
    try:
        server.serve_forever(stop_event=stop)
    finally:
        server.close()


if __name__ == "__main__":
    main()
