"""ServeGateway: a routed, supervised fleet of policy servers.

PR 10 built one :class:`~blendjax.serve.server.PolicyServer`; the
north star ("heavy traffic from millions of users") needs a *fleet*:
N replicas behind one routing front that keeps aggregate QPS scaling
near-linearly while a replica dies and respawns (the replica-level
scale-out half of the TPU serving playbook, arXiv:2605.25645, on top
of PR 10's batch admission).  The gateway is one process/thread with a
client-facing ROUTER socket and one DEALER backend per replica:

- **episode-lease affinity**: the ``{slot, episode}`` lease every reset
  reply already carries becomes the session token.  The gateway rewrites
  the replica's episode id to a gateway-unique lease id, remembers
  ``lease -> (replica, slot, real episode)``, and pins every later
  ``step``/``close`` of that episode to the replica that owns its
  KV-cache row (``gateway_affinity_hits``).  Lease ids are never reused
  across replica incarnations, so a respawned replica can never be
  reached through a dead episode's lease;
- **load-spread fresh episodes**: each replica's ``telemetry`` RPC is
  scraped on an interval (cheap and cached — never per-request) for
  queue depth, live episodes and the ``SERVE_STAGES`` ``queue_wait``
  p99; a ``reset`` goes to the lowest-scoring healthy, non-draining
  replica (rotation breaks ties; ``gateway_rebalances`` counts the
  routes where load overrode rotation).  Between scrapes an optimistic
  local live-count keeps a burst of resets spreading instead of piling
  onto the last scrape's winner;
- **supervision**: replicas live under the existing
  :class:`~blendjax.btt.watchdog.FleetWatchdog`/:class:`~blendjax.serve.
  server.ServerFleet` vocabulary.  A replica that stops answering
  scrapes (or whose death the watchdog reports via
  :meth:`ServeGateway.notify_replica_death`) is **quarantined**: its
  leases are invalidated, steps against them get the actionable
  stale-lease error (``gateway_stale_lease_redirects``) and resume
  after ``reset()`` on a healthy replica; the respawned replica rejoins
  on its first answered scrape (``gateway_replica_respawns``).
  :meth:`ServeGateway.drain` stops fresh episodes to a replica while
  its live episodes finish — the rolling-restart primitive;
- **exactly-once through the extra hop**: the gateway forwards
  ``wire.BTMID_KEY`` verbatim, re-forwards a retry of an in-flight
  request to the SAME replica (whose dedupe/reply cache keeps it
  exactly-once), and keeps its own bounded reply cache of mutating
  replies so a retry whose reply was lost between gateway and client is
  answered without touching the fleet again.  The client-side
  discipline (:func:`blendjax.btt.rpc.exactly_once_rpc`) rides through
  unchanged;
- **multi-model routing**: requests carrying ``model`` in the envelope
  route only to replicas hosting that model id (learned from the
  scrape), composing with the server-side multi-model hosting
  (per-model slot pools and bucket caches — see server.py).

Every forwarded reply is stamped with the serving replica's id
(``replica``), so a misbehaving replica is diagnosable from a client
traceback alone (``ServeClient`` surfaces it in ``ServeRPCError`` text
and span args).

Telemetry: ``GATEWAY_EVENTS`` counters + ``GATEWAY_STAGES``
(``gw_route``/``gw_forward``/``gw_reply``) with latency histograms,
zero-filled by every ``TelemetryHub.scrape()``; the gateway answers the
``telemetry`` RPC itself, so ``ServeClient.register_with_hub`` makes it
a scrapeable remote like any replica.

Run a gateway as a process::

    python -m blendjax.serve.gateway --address tcp://127.0.0.1:24100 \
        --replica tcp://127.0.0.1:24000 --replica tcp://127.0.0.1:24001

or in-process via :func:`start_gateway_thread`.  See docs/serving.md
("ServeGateway").
"""

from __future__ import annotations

import argparse
import logging
import os
import signal
import subprocess
import sys
import threading
import time
import zlib
from collections import OrderedDict, deque

from blendjax import wire
from blendjax.btt import shm_rpc
from blendjax.obs.histogram import LatencyHistogram
from blendjax.obs.spans import make_span, now_us
from blendjax.serve.server import (
    MUTATING_CMDS,
    REPLY_CACHE_DEPTH,
    drain_socket,
)
from blendjax.utils.timing import StageTimer, fleet_counters

logger = logging.getLogger("blendjax")

#: Bound on the in-flight route table (mid -> client ident + replica).
#: Routes pop when their reply forwards; entries past the bound are the
#: leftovers of clients that gave up — evicted oldest-first.
ROUTE_CACHE_DEPTH = 8192

#: Commands the gateway answers itself (never forwarded): aggregate
#: capability/stats/telemetry, the drain lifecycle, the weight-bus
#: canary lifecycle (docs/weight_bus.md), and the sharded control
#: plane's versioned routing-state publication (``gw_snapshot``,
#: worker mode only — see :class:`ShardedGateway`).
GATEWAY_CMDS = ("hello", "stats", "telemetry", "drain", "undrain",
                "canary", "promote", "rollback", "gw_snapshot")

#: Per-weight-version reply metrics kept (newest versions win): enough
#: for a canary + stable + a few predecessors, bounded regardless of
#: publish rate.
VERSION_STATS_DEPTH = 8

#: Per-scenario reply metrics kept (oldest label evicted first):
#: bounded regardless of how many scenario labels clients invent —
#: a catalog is typically a handful, this is headroom
#: (docs/scenarios.md).
SCENARIO_STATS_DEPTH = 32


class _Replica:
    """One backend replica: its DEALER channel plus the cached scrape
    state the router decides with."""

    __slots__ = (
        "id", "address", "sock", "healthy", "draining", "models",
        "queued", "live", "p99_ms", "pending_live", "last_ok",
        "incarnation", "scrape_mid", "scrape_sent", "next_scrape", "pid",
        "caps", "shm", "shm_state", "shm_next_try", "weight_version",
    )

    def __init__(self, rid, address, sock, now):
        self.id = rid
        self.address = address
        self.sock = sock
        self.healthy = True
        self.draining = False
        self.models = None     # None until the first scrape: matches any
        self.queued = 0
        self.live = 0
        self.p99_ms = 0.0
        #: fresh episodes routed here since the last scrape — the
        #: optimistic estimate that keeps a reset burst spreading
        self.pending_live = 0
        self.last_ok = now     # construction grace: one quarantine window
        self.incarnation = 0
        self.scrape_mid = None
        self.scrape_sent = 0.0
        self.next_scrape = 0.0  # scrape immediately on loop start
        self.pid = None
        self.caps = None  # PR-10 capability fields from the scrape
        #: backend ShmRPC channel (None = ZMQ): negotiated through the
        #: scrape cycle once the replica proves alive, torn down on
        #: quarantine, re-negotiated after respawn
        self.shm = None
        self.shm_state = "idle"  # idle | pending | active | off
        self.shm_next_try = 0.0
        #: scraped WeightBus version (None = no snapshot adopted yet,
        #: or a pre-bus replica) — what canary routing keys on
        self.weight_version = None

    def hosts(self, model):
        return model is None or self.models is None or model in self.models

    def load_score(self):
        """Routing score, lower = preferred: live episodes (capacity),
        queue depth (overload, weighted — queued work is latency NOW)
        and the scraped ``queue_wait`` p99 as a slow-replica penalty."""
        return (self.live + self.pending_live + 4 * self.queued
                + self.p99_ms / 100.0)

    def snapshot(self):
        return {
            "address": self.address,
            "healthy": self.healthy,
            "draining": self.draining,
            "models": sorted(self.models) if self.models else None,
            "queued": self.queued,
            "live_episodes": self.live,
            "p99_ms": round(self.p99_ms, 3),
            "incarnation": self.incarnation,
            "pid": self.pid,
            "weight_version": self.weight_version,
        }


class _Lease:
    __slots__ = ("rid", "slot", "episode", "model", "incarnation",
                 "dead", "t_use", "scenario")

    def __init__(self, rid, slot, episode, model, incarnation,
                 scenario=None):
        self.rid = rid
        self.slot = slot
        self.episode = episode  # the replica's REAL lease id
        self.model = model
        self.incarnation = incarnation
        self.dead = False
        self.t_use = time.monotonic()
        #: scenario label the episode was admitted under (None =
        #: unlabelled traffic) — every step/close inherits it for the
        #: per-scenario reply records (docs/scenarios.md)
        self.scenario = scenario


class _Route:
    __slots__ = ("ident", "rid", "inc", "cmd", "model", "gw_ep", "t0",
                 "span_trace", "t0_us", "scenario")

    def __init__(self, ident, rid, inc, cmd, model, gw_ep, span_trace,
                 t0_us, scenario=None):
        self.ident = ident
        self.rid = rid
        self.inc = inc  # replica incarnation at forward time
        self.cmd = cmd
        self.model = model
        self.gw_ep = gw_ep  # the client-visible lease id (step/close)
        self.t0 = time.perf_counter()
        self.span_trace = span_trace
        self.t0_us = t0_us
        self.scenario = scenario


class ServeGateway:
    """The routing front of a policy-server fleet (module docstring).

    Params
    ------
    address: str
        Client-facing endpoint to bind (``tcp://host:*`` binds an
        ephemeral port; resolved endpoint on :attr:`address`).
    replicas: sequence[str]
        Backend replica addresses; replica ids are ``r0..rN-1`` in
        order.
    scrape_interval_s: float
        Cached load/liveness scrape period per replica (the routing
        table refresh — never per-request).
    quarantine_after_s: float | None
        Silence horizon after which a replica is quarantined (default
        ``max(1.0, 4 * scrape_interval_s)``).
    lease_ttl_s: float | None
        Idle horizon after which a lease is forgotten (default 600 s;
        None disables).  A client that crashes without ``close()``
        leaves its lease behind — the replica reclaims the slot via its
        own ``slot_ttl_s``, but the gateway only learns through this
        sweep (the scrape carries counts, not slot identities).  A
        pruned lease's late step gets the same actionable
        reset-and-resume error as a stale one.
    """

    def __init__(self, address, replicas, *, scrape_interval_s=0.25,
                 quarantine_after_s=None, lease_ttl_s=600.0,
                 counters=None, timer=None,
                 reply_cache_depth=REPLY_CACHE_DEPTH, context=None,
                 shm_base=None, worker_index=None, n_workers=1,
                 enable_shm=True):
        import zmq

        if not replicas:
            raise ValueError("a gateway needs >= 1 replica address")
        #: sharded-data-plane worker identity (None = a standalone
        #: gateway).  A worker gateway does NOT scrape or quarantine
        #: replicas itself — replica health / drain / load / canary
        #: state arrives as versioned ``gw_snapshot`` publications from
        #: the control plane (the WeightBus publish pattern pointed at
        #: routing state), so nothing on the request path ever RPCs the
        #: control plane.  Its lease ids are congruent to
        #: ``worker_index`` mod ``n_workers``, so any party can compute
        #: a lease's owning worker with zero shared state.
        self.worker_index = None if worker_index is None \
            else int(worker_index)
        self.n_workers = int(n_workers)
        self.worker_tag = (None if self.worker_index is None
                           else f"gw{self.worker_index}")
        #: last applied control-snapshot version (worker mode; stale
        #: versions are ignored so re-ordered publishes cannot roll
        #: routing state backwards)
        self._snap_version = -1
        #: per-replica incarnation as published by the control plane —
        #: a bump means the control saw a death/restart this worker may
        #: have missed, so local leases on it must die
        self._snap_inc = {}
        self.scrape_interval_s = float(scrape_interval_s)
        self.quarantine_after_s = (
            max(1.0, 4 * self.scrape_interval_s)
            if quarantine_after_s is None else float(quarantine_after_s)
        )
        self.lease_ttl_s = (
            None if lease_ttl_s is None else float(lease_ttl_s)
        )
        self._next_lease_sweep = 0.0
        self.counters = counters if counters is not None else fleet_counters
        self.timer = timer if timer is not None else StageTimer()
        self._ctx = context or zmq.Context.instance()
        self._front = self._ctx.socket(zmq.ROUTER)
        self._front.setsockopt(zmq.LINGER, 0)
        if address.endswith(":*") or address.endswith(":0"):
            base = address.rsplit(":", 1)[0]
            port = self._front.bind_to_random_port(base)
            self.address = f"{base}:{port}"
        else:
            self._front.bind(address)
            self.address = address
        now = time.monotonic()
        self._replicas = {}
        for i, addr in enumerate(replicas):
            sock = self._ctx.socket(zmq.DEALER)
            sock.setsockopt(zmq.LINGER, 0)
            sock.connect(addr)
            self._replicas[f"r{i}"] = _Replica(f"r{i}", addr, sock, now)
        self._order = list(self._replicas)
        self._rr = 0
        self._routes = OrderedDict()   # mid -> _Route (in flight)
        self._scrapes = {}             # mid -> replica id
        self._leases = {}              # gw episode id -> _Lease
        self._lease_rev = {}           # (rid, incarnation, real ep) -> gw ep
        #: lease-id sequence.  Standalone: 0, 1, 2, ...  Worker k of N:
        #: k+N, k+2N, ... — every id ≡ k (mod N), never below N (0 is
        #: not a valid lease and ids < N would alias worker indices)
        self._ep_seq = (0 if self.worker_index is None
                        else self.worker_index)
        self._reply_cache = OrderedDict()
        self._reply_cache_depth = int(reply_cache_depth)
        #: watchdog + autoscale notices (thread-safe appends), applied
        #: on the loop.  ``("add", rid, address)`` / ``("remove", rid,
        #: None)`` are the live-resize ops: the DEALER socket is created
        #: and registered ON the loop thread (zmq sockets are not
        #: thread-safe), so ``add_replica``/``remove_replica`` stay
        #: callable from any controller thread
        self._notices = deque()
        #: next replica id the live-resize path allocates ("r<N>") —
        #: monotonic so a retired id is never reused (stale leases and
        #: in-flight routes on the old id can never alias a newcomer)
        self._rid_seq = len(replicas)
        self._rid_lock = threading.Lock()
        #: the serve_forever poller, stored so _apply_notices can
        #: register/unregister replica sockets added after loop start
        self._poller = None
        #: front-side ShmRPC transport (clients upgrade onto it exactly
        #: as against a bare server) — its bell doubles as the shared
        #: reply-wake fd for the BACKEND shm channels, so one poller
        #: entry covers every ring this process reads
        self._shm_front = None
        if enable_shm and shm_rpc.enabled():
            self._shm_front = shm_rpc.ShmRpcServer(
                base=shm_base or shm_rpc.new_base("gw"),
                counters=self.counters, who="gateway",
            )
        #: in-flight backend upgrade handshakes: mid -> (phase, rid)
        self._shm_connects = {}
        #: weight-bus canary state (docs/weight_bus.md): while a canary
        #: window is open, fresh episodes split between replicas at the
        #: canary version (``_canary_fraction`` of them, paced by the
        #: deterministic accumulator) and replicas at any OTHER known
        #: version; a rolled-back version is avoided for fresh traffic
        #: until its replicas move off it (rollback republish)
        self._canary_version = None
        self._canary_fraction = 0.0
        self._canary_acc = 0.0
        self._stable_version = None
        self._rejected_version = None
        #: per-weight-version reply metrics (requests / errors / client
        #: round-trip histogram through this gateway) — what the
        #: WeightBusController's promote/rollback verdicts read.  The
        #: lock matters: the gateway IO thread inserts/evicts while a
        #: controller thread iterates via version_stats()
        self._version_stats = OrderedDict()
        self._version_stats_lock = threading.Lock()
        #: per-scenario reply metrics (docs/scenarios.md): requests /
        #: errors / client round-trip histogram per scenario LABEL,
        #: next to the per-version records — the serve tier's view of
        #: a labelled traffic mix.  Same lock discipline as the
        #: version stats (IO thread writes, scrapers iterate).
        self._scenario_stats = OrderedDict()

    # -- admin (callable from any thread; applied under the GIL) -------------

    def drain(self, rid):
        """Stop routing FRESH episodes to ``rid``; its live episodes
        keep stepping until they close — the rolling-restart primitive.

        Idempotent: re-draining an already-draining replica is a no-op
        (returns ``False``, no second ``gateway_drains`` count), so a
        restarted autoscale controller can re-issue its decision
        against observed fleet state without double-acting.  Legal on a
        QUARANTINED replica: the flag survives quarantine and
        re-admission (``_ingest_scrape`` never touches ``draining``),
        so a victim that dies mid-drain comes back still draining.
        An unknown ``rid`` raises ``KeyError`` naming the known ids —
        never a silent no-op."""
        rep = self._replicas.get(rid)
        if rep is None:
            raise KeyError(
                f"unknown replica {rid!r}; known: {self._order}"
            )
        if rep.draining:
            return False
        rep.draining = True
        self.counters.incr("gateway_drains")
        return True

    def undrain(self, rid):
        """Re-admit a drained replica to fresh-episode routing.  Same
        contract as :meth:`drain`: idempotent (``False`` when it was
        not draining), legal while quarantined, ``KeyError`` with the
        known ids on an unknown ``rid``."""
        rep = self._replicas.get(rid)
        if rep is None:
            raise KeyError(
                f"unknown replica {rid!r}; known: {self._order}"
            )
        if not rep.draining:
            return False
        rep.draining = False
        return True

    def canary(self, version, fraction=0.25):
        """Open a canary window: route ``fraction`` of FRESH episodes
        to replicas whose scraped ``weight_version`` equals
        ``version``; the rest go to replicas at other known versions.
        Replicas at NO known version (a respawned process that has not
        caught up to the bus yet) get no fresh episodes while a window
        is open — re-admission for canary traffic is version-gated."""
        self._canary_version = int(version)
        self._canary_fraction = float(fraction)
        self._canary_acc = 0.0
        if self._rejected_version == self._canary_version:
            self._rejected_version = None  # an explicit second chance
        self.counters.incr("weight_canary_starts")
        return self._canary_version

    def promote(self):
        """The open canary version becomes stable; the window closes
        (fresh episodes stop being version-split)."""
        if self._canary_version is None:
            return False
        self._stable_version = self._canary_version
        self._canary_version = None
        self._canary_fraction = 0.0
        self.counters.incr("weight_canary_promotions")
        return True

    def rollback(self):
        """Close the canary window and REJECT its version: fresh
        episodes avoid replicas still at it (until a rollback republish
        moves them forward to the old weights)."""
        if self._canary_version is None:
            return False
        self._rejected_version = self._canary_version
        self._canary_version = None
        self._canary_fraction = 0.0
        self.counters.incr("weight_canary_rollbacks")
        return True

    def set_stable(self, version):
        """Record the stable (baseline) weight version — the
        controller's bootstrap for the first version a fleet reports."""
        self._stable_version = None if version is None else int(version)

    @property
    def canary_version(self):
        return self._canary_version

    @property
    def stable_version(self):
        return self._stable_version

    @property
    def rejected_version(self):
        return self._rejected_version

    def fleet_versions(self):
        """``{rid: scraped weight_version}`` over HEALTHY replicas."""
        return {r.id: r.weight_version
                for r in self._replicas.values() if r.healthy}

    def version_stats(self):
        """Per-weight-version reply metrics: ``{version: {"requests",
        "errors", "p50_ms", "p99_ms"}}`` (client round-trip through
        this gateway, errors included in the counts)."""
        with self._version_stats_lock:
            items = [(v, rec["requests"], rec["errors"],
                      rec["hist"].copy())
                     for v, rec in self._version_stats.items()]
        out = {}
        for v, requests, errors, hist in items:
            pct = hist.percentiles()
            out[v] = {
                "requests": requests,
                "errors": errors,
                "p50_ms": pct["p50_ms"],
                "p99_ms": pct["p99_ms"],
            }
        return out

    def _note_version_reply(self, version, is_error, latency_s):
        with self._version_stats_lock:
            rec = self._version_stats.get(version)
            if rec is None:
                rec = self._version_stats[version] = {
                    "requests": 0, "errors": 0,
                    "hist": LatencyHistogram(),
                }
                # evict oldest-first, but NEVER the stable or canary
                # record: those are exactly what the controller's
                # promote/rollback verdicts diff against, and a
                # fast-publishing learner would otherwise age the
                # stable baseline out and silently disable the p99
                # regression check
                keep = {self._stable_version, self._canary_version,
                        version}
                while len(self._version_stats) > VERSION_STATS_DEPTH:
                    victim = next(
                        (v for v in self._version_stats
                         if v not in keep),
                        None,
                    )
                    if victim is None:
                        break  # everything is load-bearing: grow
                    del self._version_stats[victim]
            rec["requests"] += 1
            if is_error:
                rec["errors"] += 1
            rec["hist"].add(latency_s)

    def scenario_stats(self):
        """Per-scenario reply metrics: ``{scenario: {"requests",
        "errors", "p50_ms", "p99_ms"}}`` — client round-trip through
        this gateway per traffic label, the serve tier's per-scenario
        QPS/latency record (docs/scenarios.md)."""
        with self._version_stats_lock:
            items = [(s, rec["requests"], rec["errors"],
                      rec["hist"].copy())
                     for s, rec in self._scenario_stats.items()]
        out = {}
        for s, requests, errors, hist in items:
            pct = hist.percentiles()
            out[s] = {
                "requests": requests,
                "errors": errors,
                "p50_ms": pct["p50_ms"],
                "p99_ms": pct["p99_ms"],
            }
        return out

    def _note_scenario_reply(self, scenario, is_error, latency_s):
        with self._version_stats_lock:
            rec = self._scenario_stats.get(scenario)
            if rec is None:
                rec = self._scenario_stats[scenario] = {
                    "requests": 0, "errors": 0,
                    "hist": LatencyHistogram(),
                }
                while len(self._scenario_stats) > SCENARIO_STATS_DEPTH:
                    self._scenario_stats.popitem(last=False)
            rec["requests"] += 1
            if is_error:
                rec["errors"] += 1
            rec["hist"].add(latency_s)
        self.counters.incr("scenario_serve_requests")

    def notify_replica_death(self, idx_or_rid, exit_code=None):
        """Watchdog ``on_death`` hook: quarantine the replica NOW
        instead of waiting out the scrape silence horizon."""
        self._notices.append(("death", self._rid(idx_or_rid)))

    def notify_replica_respawn(self, idx_or_rid, proc=None):
        """Watchdog ``on_respawn`` hook: probe the replica immediately
        so re-admission does not wait for the next scheduled scrape."""
        self._notices.append(("respawn", self._rid(idx_or_rid)))

    def _rid(self, idx_or_rid):
        return (idx_or_rid if isinstance(idx_or_rid, str)
                else f"r{int(idx_or_rid)}")

    def add_replica(self, address, rid=None):
        """Admit a NEW replica to the route set (autoscale scale-up).
        Callable from any thread: allocates a never-reused id and
        enqueues the admission; the loop thread creates and registers
        the DEALER socket.  The newcomer is scraped immediately and
        joins fresh-episode routing once it answers.  Returns the id."""
        with self._rid_lock:
            if rid is None:
                rid = f"r{self._rid_seq}"
                self._rid_seq += 1
            else:
                # an explicit id (fleet-index alignment) advances the
                # sequence past it so later automatic ids cannot alias
                num = rid[1:]
                if rid.startswith("r") and num.isdigit():
                    self._rid_seq = max(self._rid_seq, int(num) + 1)
        self._notices.append(("add", rid, address))
        return rid

    def remove_replica(self, rid):
        """Retire ``rid`` from the gateway entirely (autoscale
        scale-down, after its drain reached zero live leases).  Any
        lease still on it is marked dead — the owning client gets the
        actionable stale-lease error, exactly the quarantine path —
        so removal is safe even when the drain was cut short."""
        self._notices.append(("remove", rid, None))

    def replica_ids(self):
        """The CURRENT route-set ids (admissions/removals applied on
        the loop thread may lag an ``add_replica`` by one loop tick)."""
        return list(self._order)

    def lease_count(self, rid):
        """Live (non-dead) leases owned by ``rid`` — what an autoscale
        drain polls toward zero before retiring the process."""
        return sum(1 for lease in list(self._leases.values())
                   if lease.rid == rid and not lease.dead)

    def replica_snapshots(self):
        """Per-replica routing-state snapshots (healthy / draining /
        load), the scrape surface controller decisions read."""
        return {r.id: r.snapshot() for r in list(self._replicas.values())}

    def _apply_notices(self):
        while self._notices:
            kind, rid, payload = (self._notices.popleft() + (None,))[:3]
            if kind == "add":
                self._admit_replica(rid, payload)
                continue
            rep = self._replicas.get(rid)
            if rep is None:
                continue
            if kind == "remove":
                self._retire_replica(rep)
            elif kind == "death":
                self._quarantine(rep)
            else:  # respawn: probe now
                rep.next_scrape = 0.0

    def _admit_replica(self, rid, address):
        import zmq

        if rid in self._replicas:
            return  # idempotent against a re-enqueued admission
        sock = self._ctx.socket(zmq.DEALER)
        sock.setsockopt(zmq.LINGER, 0)
        sock.connect(address)
        rep = _Replica(rid, address, sock, time.monotonic())
        self._replicas[rid] = rep
        self._order.append(rid)
        if self._poller is not None:
            self._poller.register(sock, zmq.POLLIN)
        logger.info("gateway: replica %s (%s) admitted", rid, address)

    def _retire_replica(self, rep):
        self._demote_backend(rep, "replica retired")
        for lease in self._leases.values():
            if lease.rid == rep.id:
                lease.dead = True
        for mid in [m for m, r in self._scrapes.items() if r == rep.id]:
            self._scrapes.pop(mid, None)
        if self._poller is not None:
            try:
                self._poller.unregister(rep.sock)
            except KeyError:
                pass
        try:
            rep.sock.close(0)
        except Exception:  # noqa: BLE001 - teardown best-effort
            pass
        self._replicas.pop(rep.id, None)
        if rep.id in self._order:
            self._order.remove(rep.id)
        self._rr = self._rr % max(1, len(self._order))
        logger.info("gateway: replica %s (%s) retired", rep.id,
                    rep.address)

    # -- lease + quarantine bookkeeping --------------------------------------

    def _drop_lease(self, gw_ep):
        lease = self._leases.pop(gw_ep, None)
        if lease is not None:
            self._lease_rev.pop(
                (lease.rid, lease.incarnation, lease.episode), None
            )

    def _demote_backend(self, rep, reason, backoff_s=2.0):
        """Drop a replica's shm channel and fall back to its DEALER
        socket (re-negotiated through the scrape cycle)."""
        if rep.shm is not None:
            try:
                rep.shm.close(unlink=True)
            except Exception:  # noqa: BLE001 - teardown best-effort
                pass
            rep.shm = None
            logger.warning("gateway: replica %s shm channel demoted "
                           "(%s)", rep.id, reason)
        if rep.shm_state != "off":
            rep.shm_state = "idle"
            rep.shm_next_try = time.monotonic() + backoff_s
        for mid in [m for m, entry in self._shm_connects.items()
                    if entry[1] == rep.id]:
            entry = self._shm_connects.pop(mid)
            if len(entry) > 2 and entry[2] is not None:
                try:
                    entry[2].close(unlink=True)
                except Exception:  # noqa: BLE001
                    pass

    def _quarantine(self, rep):
        if not rep.healthy:
            return
        self._demote_backend(rep, "replica quarantined", backoff_s=0.5)
        rep.healthy = False
        rep.incarnation += 1
        rep.pending_live = 0
        rep.queued = 0
        rep.live = 0
        # the respawned process starts with NO adopted snapshot: until
        # a scrape reports its (re-synced) version, canary routing must
        # not treat it as caught up
        rep.weight_version = None
        for lease in self._leases.values():
            if lease.rid == rep.id:
                # kept (marked) rather than dropped so the episode's
                # next step gets the SPECIFIC stale-lease error naming
                # the dead replica, not a generic unknown-lease one
                lease.dead = True
        self.counters.incr("gateway_replica_quarantined")
        logger.warning("gateway: replica %s (%s) quarantined",
                       rep.id, rep.address)

    # -- scrape loop ---------------------------------------------------------

    def _scrape_tick(self):
        import zmq

        now = time.monotonic()
        if self.worker_index is not None:
            # worker mode: the control plane owns scrapes, quarantine
            # verdicts and re-admission (published via gw_snapshot) —
            # only the local lease-TTL sweep below runs here
            self._lease_sweep(now)
            return
        for rep in self._replicas.values():
            if rep.scrape_mid is not None and \
                    now - rep.scrape_sent > self.scrape_interval_s * 2:
                # scrape lost (dead replica or drop): give up on the
                # mid so the next interval re-probes
                self._scrapes.pop(rep.scrape_mid, None)
                rep.scrape_mid = None
            if rep.scrape_mid is None and now >= rep.next_scrape:
                msg = {"cmd": "telemetry"}
                mid = wire.stamp_message_id(msg)
                try:
                    # DONTWAIT: a dead replica's pipe must not fill up
                    # with scrapes and block the gateway loop — the
                    # silence horizon quarantines it instead
                    wire.send_message_dealer(rep.sock, msg,
                                             flags=zmq.DONTWAIT)
                except zmq.ZMQError:  # Again included: skip this round
                    continue
                rep.scrape_mid = mid
                rep.scrape_sent = now
                rep.next_scrape = now + self.scrape_interval_s
                self._scrapes[mid] = rep.id
            if rep.healthy and now - rep.last_ok > self.quarantine_after_s:
                self._quarantine(rep)
        self._lease_sweep(now)

    def _lease_sweep(self, now):
        """Abandoned-episode sweep: a client that crashed without
        ``close()`` must not leak a lease forever (the replica reclaims
        the slot via ``slot_ttl_s``; this is the gateway's analogue).
        Swept on the scrape cadence, amortized."""
        if self.lease_ttl_s is not None and now >= self._next_lease_sweep:
            self._next_lease_sweep = now + max(1.0, self.lease_ttl_s / 4)
            cutoff = now - self.lease_ttl_s
            for gw_ep in [ep for ep, lease in self._leases.items()
                          if lease.t_use < cutoff]:
                self._drop_lease(gw_ep)

    def _ingest_scrape(self, rep, reply):
        rep.last_ok = time.monotonic()
        rep.scrape_mid = None
        pid = reply.get("pid")
        if rep.healthy and rep.pid is not None and pid is not None \
                and pid != rep.pid:
            # SILENT restart: the replica answered a new pid without
            # ever missing a scrape (external restart, or a respawn
            # faster than the quarantine horizon).  Its slot pool is
            # fresh — old leases must die NOW, and the incarnation must
            # bump so the new process's recycled (slot, episode) pairs
            # cannot alias old gateway leases through _lease_rev
            self._quarantine(rep)
        if not rep.healthy:
            rep.healthy = True
            self.counters.incr("gateway_replica_respawns")
            logger.warning("gateway: replica %s answered again — "
                           "re-admitted", rep.id)
        models = reply.get("models")
        if models:
            rep.models = set(models)
        rep.queued = int(reply.get("queued", 0))
        rep.live = int(reply.get("live_episodes", 0))
        rep.pending_live = 0  # the scrape's live count subsumes it
        rep.pid = pid
        rep.weight_version = reply.get("weight_version")
        caps = reply.get("hello")
        if isinstance(caps, dict):
            rep.caps = caps
        stages = reply.get("stages") or {}
        rec = stages.get("queue_wait") or {}
        hist = rec.get("hist")
        if hist:
            try:
                rep.p99_ms = LatencyHistogram.from_dict(
                    hist
                ).percentiles()["p99_ms"]
            except Exception:  # noqa: BLE001 - scrape must not kill routing
                pass
        # the replica just proved alive: (re-)negotiate its shm channel
        self._maybe_upgrade_backend(rep)

    # -- backend shm upgrade (rides the scrape cycle, fully async) -----------

    def _maybe_upgrade_backend(self, rep):
        import zmq

        if (self._shm_front is None or rep.shm is not None
                or rep.shm_state in ("pending", "off")
                or time.monotonic() < rep.shm_next_try):
            return
        msg = {"cmd": "shm_connect", "host": shm_rpc.host_token()}
        mid = wire.stamp_message_id(msg)
        try:
            wire.send_message_dealer(rep.sock, msg, flags=zmq.DONTWAIT)
        except zmq.ZMQError:
            return
        rep.shm_state = "pending"
        self._shm_connects[mid] = ("connect", rep.id, None)

    def _handle_backend_upgrade(self, rep, phase, chan, reply):
        """One step of the async backend handshake (connect -> attach
        -> open), driven entirely by replies arriving on the replica's
        DEALER socket — the gateway loop never blocks on it."""
        import zmq

        def fail(permanent=False, close_chan=None):
            if close_chan is not None:
                try:
                    close_chan.close(unlink=True)
                except Exception:  # noqa: BLE001
                    pass
            rep.shm_state = "off" if permanent else "idle"
            rep.shm_next_try = time.monotonic() + 5.0

        if not rep.healthy:
            return fail(close_chan=chan)
        if phase == "connect":
            if "error" in reply or "shm_channel" not in reply:
                # a considered refusal (kill-switch, host mismatch,
                # pre-ShmRPC replica): permanent for this incarnation
                logger.info("gateway: replica %s refused shm (%s)",
                            rep.id, reply.get("error", "no channel"))
                return fail(permanent=True)
            try:
                new_chan = shm_rpc.ShmClientChannel(
                    reply["shm_channel"], reply["shm_bell"],
                    bell=self._shm_front.bell,
                )
            except Exception:  # noqa: BLE001 - degrade, never fail
                return fail()
            msg = {"cmd": "shm_attach", "channel": new_chan.name,
                   "bell": new_chan.bell_path}
            mid = wire.stamp_message_id(msg)
            try:
                wire.send_message_dealer(rep.sock, msg,
                                         flags=zmq.DONTWAIT)
            except zmq.ZMQError:
                return fail(close_chan=new_chan)
            self._shm_connects[mid] = ("attach", rep.id, new_chan)
            return
        # phase == "attach"
        if "error" in reply:
            return fail(close_chan=chan)
        try:
            chan.finish(open_timeout_ms=1000)
        except Exception:  # noqa: BLE001
            return fail(close_chan=chan)
        rep.shm = chan
        rep.shm_state = "active"
        logger.info("gateway: replica %s upgraded to shm channel %s",
                    rep.id, chan.name)

    # -- control-snapshot subscription (worker mode) -------------------------

    def _cmd_gw_snapshot(self, msg):
        """Adopt one versioned control-plane snapshot: replica health /
        drain / load / caps and the canary window, as scraped and
        decided by the :class:`ShardedGateway` control thread.  Workers
        only ever READ this consistent view — the request path never
        RPCs the control plane.  Stale versions are ignored (re-ordered
        publishes must not roll routing state backwards)."""
        if self.worker_index is None:
            return {"error": "gw_snapshot against a non-worker gateway"}
        version = int(msg.get("version", -1))
        if version <= self._snap_version:
            return {"applied": False, "version": self._snap_version}
        self._snap_version = version
        for rid, snap in (msg.get("replicas") or {}).items():
            rep = self._replicas.get(rid)
            if not isinstance(snap, dict) or rep is None:
                continue
            inc = int(snap.get("incarnation", 0))
            known = self._snap_inc.get(rid)
            if known is not None and inc > known:
                # the control plane saw a death/restart (possibly a
                # silent one) this worker may have missed: local leases
                # on the replica must die before the new incarnation's
                # recycled (slot, episode) pairs can alias them
                self._quarantine(rep)
            self._snap_inc[rid] = inc
            if not snap.get("healthy", False):
                self._quarantine(rep)
            elif not rep.healthy:
                rep.healthy = True
                self.counters.incr("gateway_replica_respawns")
            rep.draining = bool(snap.get("draining", False))
            models = snap.get("models")
            if models:
                rep.models = set(models)
            rep.queued = int(snap.get("queued", 0))
            rep.live = int(snap.get("live", 0))
            rep.pending_live = 0  # the snapshot's live count subsumes it
            rep.p99_ms = float(snap.get("p99_ms") or 0.0)
            rep.pid = snap.get("pid")
            rep.weight_version = snap.get("weight_version")
            caps = snap.get("caps")
            if isinstance(caps, dict):
                rep.caps = caps
            if rep.healthy:
                # the control plane vouches for the replica (its scrape
                # answered): probe the shm upgrade off the snapshot
                # cadence, exactly where the standalone gateway probes
                # off its own scrape ingest
                rep.last_ok = time.monotonic()
                self._maybe_upgrade_backend(rep)
        weights = msg.get("weights") or {}
        self._canary_version = weights.get("canary_version")
        self._canary_fraction = float(
            weights.get("canary_fraction") or 0.0
        )
        self._stable_version = weights.get("stable_version")
        self._rejected_version = weights.get("rejected_version")
        self.counters.incr("gateway_snapshot_applies")
        return {"applied": True, "version": version}

    # -- gateway-level commands ----------------------------------------------

    def _cmd_hello(self, msg):
        models = set()
        caps = None
        for rep in self._replicas.values():
            models |= rep.models or set()
            if caps is None and rep.healthy and rep.caps is not None:
                caps = rep.caps
        out = {}
        if caps is not None:
            # a representative replica's PR-10 capability fields
            # (obs_dim, slots, max_batch, buckets, int8, serial, model)
            # so hello consumers written against a bare server work
            # unchanged pointed at a gateway
            out.update(caps)
        out.update({
            "gateway": True,
            "replicas": {r.id: r.snapshot()
                         for r in self._replicas.values()},
            "models": sorted(models),
            "shm": (self._shm_front.info()
                    if self._shm_front is not None else None),
            "pid": os.getpid(),
        })
        if self.worker_tag is not None:
            out["gw_worker"] = self.worker_tag
            out["n_workers"] = self.n_workers
        return out

    def _cmd_stats(self, msg):
        return {
            "gateway": True,
            "replicas": {r.id: r.snapshot()
                         for r in self._replicas.values()},
            "leases": len(self._leases),
            "routes_inflight": len(self._routes),
            "counters": self.counters.snapshot(),
            "weights": self._weights_snapshot(),
            "scenarios": self.scenario_stats(),
            "pid": os.getpid(),
        }

    def _weights_snapshot(self):
        """The rollout state one dict deep: canary window, stable /
        rejected versions, per-replica versions, per-version metrics."""
        return {
            "canary_version": self._canary_version,
            "canary_fraction": self._canary_fraction,
            "stable_version": self._stable_version,
            "rejected_version": self._rejected_version,
            "fleet_versions": self.fleet_versions(),
            "version_stats": {
                str(v): rec for v, rec in self.version_stats().items()
            },
        }

    def _cmd_telemetry(self, msg):
        """The gateway's OWN telemetry in the TelemetryHub merge shape
        (``ServeClient.register_with_hub`` against a gateway address
        scrapes the routing tier, not a replica)."""
        return {
            "gateway": True,
            "pid": os.getpid(),
            "counters": self.counters.snapshot(),
            "stages": self.timer.snapshot_serialized(),
            "replicas": {r.id: r.snapshot()
                         for r in self._replicas.values()},
            "weights": self._weights_snapshot(),
            "scenarios": self.scenario_stats(),
        }

    def _cmd_canary(self, msg):
        version = msg.get("version")
        if version is None:
            return {"error": "canary needs a version"}
        v = self.canary(version, float(msg.get("fraction", 0.25)))
        return {"canary_version": v,
                "fraction": self._canary_fraction}

    def _cmd_promote(self, msg):
        promoted = self.promote()
        return {"promoted": promoted,
                "stable_version": self._stable_version}

    def _cmd_rollback(self, msg):
        rolled = self.rollback()
        return {"rolled_back": rolled,
                "rejected_version": self._rejected_version}

    def _cmd_drain(self, msg):
        return self._drain_cmd(msg, True)

    def _cmd_undrain(self, msg):
        return self._drain_cmd(msg, False)

    def _drain_cmd(self, msg, draining):
        rid = msg.get("replica")
        if rid not in self._replicas:
            return {"error": (
                f"unknown replica {rid!r}; known: {self._order}"
            )}
        (self.drain if draining else self.undrain)(rid)
        return {"draining": [r.id for r in self._replicas.values()
                             if r.draining]}

    # -- routing -------------------------------------------------------------

    def _route_fresh(self, model):
        """Pick the replica a fresh episode goes to: healthy, not
        draining, hosting ``model``; lowest load score, with ties going
        to the ROTATION candidate (eligible replicas are ranked in
        rotation order and ``min`` keeps the first on equal scores), so
        equal-load fleets round-robin instead of pinning to the
        lowest-sorting replica id.

        Weight-bus overlays (docs/weight_bus.md): a ROLLED-BACK
        version's replicas are avoided while any alternative exists,
        and an open canary window splits fresh episodes between the
        canary version's replicas (``_canary_fraction`` of them, paced
        deterministically) and other KNOWN-version replicas — a replica
        at no known version (respawned, not yet caught up to the bus)
        gets nothing until a scrape shows it synced."""
        n = len(self._order)
        eligible = []  # in rotation order starting at the pointer
        for k in range(n):
            r = self._replicas[self._order[(self._rr + k) % n]]
            if r.healthy and not r.draining and r.hosts(model):
                eligible.append(r)
        if not eligible:
            return None
        self._rr = (self._rr + 1) % n
        if self._rejected_version is not None:
            safe = [r for r in eligible
                    if r.weight_version != self._rejected_version]
            if safe:
                # availability first: with NOWHERE else to go, the
                # rejected version still serves rather than refusing
                eligible = safe
        if self._canary_version is not None:
            can = [r for r in eligible
                   if r.weight_version == self._canary_version]
            rest = [r for r in eligible
                    if r.weight_version is not None
                    and r.weight_version != self._canary_version]
            if can and rest:
                self._canary_acc += self._canary_fraction
                if self._canary_acc >= 1.0:
                    self._canary_acc -= 1.0
                    eligible = can
                    self.counters.incr("weight_canary_routes")
                else:
                    eligible = rest
            elif can or rest:
                # only one side exists (the whole fleet converged, or
                # nothing has): no split to pace — but unknown-version
                # replicas stay excluded until they catch up
                if can:
                    self.counters.incr("weight_canary_routes")
                eligible = can or rest
            # neither side known: fall through ungated (a pre-bus
            # fleet must keep serving under an accidental canary)
        cand = eligible[0]
        chosen = min(eligible, key=lambda r: r.load_score())
        if chosen is not cand:
            self.counters.incr("gateway_rebalances")
        return chosen

    def _forward(self, rep, ident, msg, cmd, model, gw_ep,
                 scenario=None):
        """Record the route and relay the request (BTMID verbatim).
        The send is NON-blocking: a replica whose pipe is full (stalled
        process, dead peer past the HWM) must cost its own clients an
        actionable error, never freeze the whole gateway loop."""
        import zmq

        mid = msg.get(wire.BTMID_KEY)
        span_ctx = msg.get(wire.SPAN_KEY)
        trace = (span_ctx or {}).get("trace") \
            if isinstance(span_ctx, dict) else None
        prior = self._routes.get(mid) if mid is not None else None
        if mid is not None:
            self._routes[mid] = _Route(ident, rep.id, rep.incarnation,
                                       cmd, model, gw_ep, trace,
                                       now_us(), scenario)
            while len(self._routes) > ROUTE_CACHE_DEPTH:
                self._routes.popitem(last=False)
        t0 = time.perf_counter()
        if rep.shm is not None:
            # the upgraded wire first; a full ring falls through to the
            # DEALER socket (same replica, same mid — the wires differ,
            # the discipline does not), a dead ring demotes
            try:
                frames = wire.encode(msg, raw_buffers=True)
                if rep.shm.send(frames, timeout_ms=0):
                    self.timer.add("gw_forward",
                                   time.perf_counter() - t0)
                    self.counters.incr("gateway_routed")
                    return
            except ValueError:
                pass  # oversized for the ring: this one rides ZMQ
            except (OSError, EOFError) as exc:
                self._demote_backend(
                    rep, f"{type(exc).__name__}: {exc}"
                )
        try:
            wire.send_message_dealer(rep.sock, msg, raw_buffers=True,
                                     flags=zmq.DONTWAIT)
        except zmq.Again:
            # pipe to the replica is full: it is stalled or gone.  If
            # this was a RE-forward of an in-flight retry, the original
            # send was already delivered and still owes a reply —
            # restore that route and stay silent (an error here would
            # be cached against a request the replica may yet apply).
            # A FIRST forward is answered now, actionably (retriable),
            # instead of parking in a queue that may never drain.
            if prior is not None:
                self._routes[mid] = prior
                prior.ident = ident
                return
            if mid is not None:
                self._routes.pop(mid, None)
            self._local_reply(ident, msg, {"error": (
                f"replica {rep.id} send queue full (stalled or "
                "unreachable): retry, or reset() after its respawn"
            )}, span_name=f"gateway:{cmd}", cache=False)
            return
        except zmq.ZMQError:
            if mid is not None:
                if prior is not None:
                    self._routes[mid] = prior
                    prior.ident = ident
                else:
                    self._routes.pop(mid, None)
            return
        self.timer.add("gw_forward", time.perf_counter() - t0)
        self.counters.incr("gateway_routed")

    def _local_reply(self, ident, msg, reply, *, span_name, cache=True):
        """Answer a request from the gateway itself (control commands,
        stale-lease errors, cache hits): stamp mid + span, cache
        mutating replies so retries stay local, send.

        ``cache=False`` for TRANSIENT transport/routing errors ("no
        healthy replica", "send queue full"): those are not processing
        outcomes, and caching them would answer a same-mid retry with
        the stale error after the fleet has already healed — the
        advertised remediation would be unreachable for that RPC."""
        mid = msg.get(wire.BTMID_KEY)
        if "error" in reply:
            self.counters.incr("gateway_errors")
        if self.worker_tag is not None and "gw_worker" not in reply:
            # every worker-answered reply names its worker, so a wedged
            # worker is diagnosable from a client traceback alone
            reply["gw_worker"] = self.worker_tag
        span_ctx = msg.get(wire.SPAN_KEY)
        if isinstance(span_ctx, dict) and span_ctx.get("trace") is not None:
            reply = dict(reply)
            reply[wire.SPANS_KEY] = [make_span(
                span_name, now_us(), trace=span_ctx["trace"],
                cat="gateway",
            )]
        if mid is not None:
            reply[wire.BTMID_KEY] = mid
            if cache and msg.get("cmd") in MUTATING_CMDS:
                self._cache_reply(mid, reply)
        self._send_client(ident, reply)

    def _cache_reply(self, mid, reply):
        self._reply_cache[mid] = reply
        while len(self._reply_cache) > self._reply_cache_depth:
            self._reply_cache.popitem(last=False)

    def _send_client(self, ident, reply):
        import zmq

        if ident is not None and getattr(ident, "shm_channel", False):
            # the request arrived on the shm front: the reply rides the
            # same channel (a dead one is dropped; the client demotes
            # and its same-mid retry re-fetches from the reply cache)
            if self._shm_front is not None and self._shm_front.send(
                ident, reply, raw_buffers=True
            ):
                self.counters.incr("gateway_replies")
            return
        try:
            wire.send_message_router(self._front, ident, reply,
                                     raw_buffers=True)
            self.counters.incr("gateway_replies")
        except zmq.ZMQError:
            pass  # client gone; its retry will re-dial

    def _handle_client(self, ident, msg):
        t_route = time.perf_counter()
        self.counters.incr("gateway_requests")
        mid = msg.get(wire.BTMID_KEY)
        cmd = msg.get("cmd")
        if mid is not None and cmd in MUTATING_CMDS \
                and mid in self._reply_cache:
            # retry of a request whose reply the client lost: answered
            # from the gateway cache — the fleet never sees it again
            self.counters.incr("gateway_cache_hits")
            self._send_client(ident, self._reply_cache[mid])
            return
        if mid is not None and mid in self._routes:
            # retry of an IN-FLIGHT forward: re-point the client route
            # and re-send to the SAME replica, whose own dedupe/reply
            # cache keeps the retry exactly-once end-to-end.  A retried
            # step/close carries the GATEWAY lease id again, so it is
            # rewritten through the lease exactly like a first send.
            route = self._routes[mid]
            route.ident = ident
            rep = self._replicas.get(route.rid)
            lease = (self._leases.get(route.gw_ep)
                     if route.gw_ep is not None else None)
            rewritable = route.cmd == "reset" or (
                lease is not None and not lease.dead
            )
            if rep is not None and rep.healthy and rewritable:
                if lease is not None:
                    msg["slot"] = lease.slot
                    msg["episode"] = lease.episode
                self.counters.incr("gateway_dup_inflight")
                self._forward(rep, ident, msg, route.cmd, route.model,
                              route.gw_ep, scenario=route.scenario)
                return
            # the replica died holding the request (or the lease did):
            # drop the route and fall through to fresh handling (a
            # reset re-routes; a step's dead lease errors actionably)
            del self._routes[mid]
        if cmd in GATEWAY_CMDS:
            handler = getattr(self, f"_cmd_{cmd}")
            try:
                reply = handler(msg)
            except Exception as exc:  # noqa: BLE001 - surfaced to client
                logger.exception("gateway: %r failed", cmd)
                reply = {"error": f"{type(exc).__name__}: {exc}"}
            if cmd == "hello" and mid is not None \
                    and "obs_dim" not in reply:
                # startup window: no scrape has delivered capability
                # fields yet — forward THIS hello to a healthy replica
                # (the reply path stashes its caps and overlays the
                # gateway fields), so PR-10 hello consumers never see a
                # capability-less reply while the fleet is up
                rep = next((r for r in self._replicas.values()
                            if r.healthy), None)
                if rep is not None:
                    self.timer.add("gw_route",
                                   time.perf_counter() - t_route)
                    self._forward(rep, ident, msg, "hello", None, None)
                    return
            self.timer.add("gw_route", time.perf_counter() - t_route)
            self._local_reply(ident, msg, reply,
                              span_name=f"gateway:{cmd}")
            return
        if mid is None:
            # forwarded replies route back to clients BY correlation id
            # (the replica's reply carries no client identity): a
            # mid-less request would execute on the replica with its
            # reply unroutable — reject it here, actionably, instead
            self.timer.add("gw_route", time.perf_counter() - t_route)
            self._local_reply(ident, msg, {"error": (
                f"{cmd!r} through a gateway needs a correlation id "
                "(wire.stamp_message_id); its reply could not be "
                "routed back otherwise"
            )}, span_name=f"gateway:{cmd}")
            return
        if cmd == "reset":
            model = msg.get("model")
            # the traffic label rides the admission request and is
            # inherited by the episode's lease (docs/scenarios.md);
            # replicas ignore the extra key
            scenario = msg.get("scenario")
            rep = self._route_fresh(model)
            self.timer.add("gw_route", time.perf_counter() - t_route)
            if rep is None:
                self._local_reply(ident, msg, {"error": (
                    "no healthy replica"
                    + (f" hosting model {model!r}" if model else "")
                    + f" (fleet: {self._order}); retry after respawn"
                )}, span_name="gateway:reset", cache=False)
                return
            rep.pending_live += 1
            self._forward(rep, ident, msg, "reset", model, None,
                          scenario=scenario)
            return
        if cmd in ("step", "close"):
            gw_ep = msg.get("episode")
            lease = self._leases.get(gw_ep)
            if lease is None or lease.dead:
                self.timer.add("gw_route",
                               time.perf_counter() - t_route)
                if lease is not None:
                    self._drop_lease(gw_ep)
                self.counters.incr("gateway_stale_lease_redirects")
                if cmd == "close":
                    # mirror the server's stale-close semantics: a
                    # no-op close is answered, never an error
                    self._local_reply(ident, msg, {"closed": False},
                                      span_name="gateway:close")
                    return
                dead_on = (f" (replica {lease.rid} died)"
                           if lease is not None else "")
                self._local_reply(ident, msg, {
                    "error": (
                        f"stale episode lease {gw_ep!r}{dead_on}: "
                        "reset() and resume on a healthy replica"
                    ),
                    "lease": "stale" if lease is not None else "unknown",
                }, span_name=f"gateway:{cmd}")
                return
            rep = self._replicas[lease.rid]
            # rewrite to the replica's REAL lease; everything else —
            # mid, span context, obs buffers — rides verbatim
            msg["slot"] = lease.slot
            msg["episode"] = lease.episode
            lease.t_use = time.monotonic()
            self.counters.incr("gateway_affinity_hits")
            self.timer.add("gw_route", time.perf_counter() - t_route)
            self._forward(rep, ident, msg, cmd, lease.model, gw_ep,
                          scenario=lease.scenario)
            return
        self.timer.add("gw_route", time.perf_counter() - t_route)
        self._local_reply(ident, msg, {
            "error": f"unknown serve command {cmd!r}"
        }, span_name="gateway:unknown")

    # -- reply path ----------------------------------------------------------

    def _handle_replica_reply(self, rep, reply):
        t0 = time.perf_counter()
        # ANY reply on this socket proves the process is alive: a
        # replica busy in a long compile must not get quarantined for
        # missing a scrape while it is actively answering traffic
        # (re-admission itself stays scrape-driven)
        rep.last_ok = time.monotonic()
        mid = reply.get(wire.BTMID_KEY)
        if mid is not None and mid in self._shm_connects:
            phase, rid, chan = self._shm_connects.pop(mid)
            self._handle_backend_upgrade(self._replicas[rid], phase,
                                         chan, reply)
            return
        if mid is not None and mid in self._scrapes:
            rid = self._scrapes.pop(mid)
            self._ingest_scrape(self._replicas[rid], reply)
            return
        route = self._routes.get(mid) if mid is not None else None
        if route is None:
            # a dup (cache hit + original), or a client that gave up
            self.counters.incr("stale_replies")
            return
        if route.rid != rep.id:
            # late reply from a replica this request was re-routed
            # AWAY from (quarantine mid-retry): the live route belongs
            # to the new replica — leave it for the genuine reply
            self.counters.incr("stale_replies")
            return
        del self._routes[mid]
        reply["replica"] = rep.id
        if self.worker_tag is not None:
            reply["gw_worker"] = self.worker_tag
        wv = reply.get("weight_version")
        if wv is not None:
            # per-version rollout metrics: every stamped reply lands in
            # its version's request/error/latency record — what the
            # canary controller's promote/rollback verdicts read
            self._note_version_reply(wv, "error" in reply,
                                     time.perf_counter() - route.t0)
        if route.scenario is not None:
            # per-scenario traffic metrics next to the per-version
            # ones: a labelled mix's QPS/p99 is attributable per
            # scenario from the gateway alone (docs/scenarios.md)
            self._note_scenario_reply(route.scenario, "error" in reply,
                                      time.perf_counter() - route.t0)
        if "error" in reply:
            # name the replica in the traceback the client will raise
            reply["error"] = f"replica {rep.id}: {reply['error']}"
            if reply.get("lease") in ("unknown", "stale") \
                    and route.gw_ep is not None:
                # the replica disowned the lease (evicted/restarted):
                # forget it so the next step short-circuits here.  This
                # is the SAME client-visible event as the gateway's own
                # dead-lease redirect (which side answers first is a
                # race between watchdog respawn and client retry), so
                # it counts under the same name
                self._drop_lease(route.gw_ep)
                self.counters.incr("gateway_stale_lease_redirects")
        elif route.cmd == "reset":
            if not rep.healthy or route.inc != rep.incarnation:
                # a reset reply drained AFTER the replica was
                # quarantined — or from an incarnation older than the
                # current one (a silent restart was detected between
                # forward and reply): registering a live lease here
                # would point the client's steps at a dead slot — and
                # poison _lease_rev for the new incarnation's recycled
                # episode ids.  Drop it; the client's retry re-routes
                # the reset to a healthy replica.
                self.counters.incr("stale_replies")
                return
            real_ep = reply.get("episode")
            key = (rep.id, rep.incarnation, real_ep)
            gw_ep = self._lease_rev.get(key)
            if gw_ep is None:
                # worker mode strides by the worker count, keeping
                # every lease id ≡ worker_index (mod n_workers) — the
                # consistent-hash ownership rule the sharded front and
                # every client can evaluate statelessly
                self._ep_seq += (1 if self.worker_index is None
                                 else self.n_workers)
                gw_ep = self._ep_seq
                self._leases[gw_ep] = _Lease(
                    rep.id, reply.get("slot"), real_ep, route.model,
                    rep.incarnation, scenario=route.scenario,
                )
                self._lease_rev[key] = gw_ep
            reply["episode"] = gw_ep
        elif route.cmd == "close":
            self._drop_lease(route.gw_ep)
        elif route.cmd == "hello":
            # a forwarded startup hello: stash the replica's capability
            # fields for every later gateway-local hello, and overlay
            # the gateway's own fields on THIS reply
            rep.caps = {
                k: reply[k]
                for k in ("model", "obs_dim", "slots", "serial", "int8",
                          "max_batch", "buckets")
                if k in reply
            }
            reply.update(self._cmd_hello({}))
        if route.span_trace is not None:
            spans = reply.setdefault(wire.SPANS_KEY, [])
            spans.append(make_span(
                f"gateway:{route.cmd}", route.t0_us,
                trace=route.span_trace, cat="gateway",
            ))
        if mid is not None and route.cmd in MUTATING_CMDS:
            self._cache_reply(mid, reply)
        self._send_client(route.ident, reply)
        self.timer.add("gw_reply", time.perf_counter() - t0)

    # -- serving -------------------------------------------------------------

    def _drain_front(self):
        import zmq

        def handle(out):
            ident, msg = out
            reply = shm_rpc.control_reply(self._shm_front, msg)
            if reply is not None:
                # transport negotiation with THIS gateway — answered
                # here (uncounted), never forwarded to the fleet
                try:
                    wire.send_message_router(self._front, ident, reply)
                except zmq.ZMQError:
                    pass
                return
            self._handle_client(ident, msg)

        drain_socket(
            lambda: wire.recv_message_router(self._front,
                                             flags=zmq.NOBLOCK),
            handle,
            self.counters, "gateway", "client request",
        )

    def _drain_front_shm(self):
        if self._shm_front is None:
            return

        def handle(chan, msg):
            reply = shm_rpc.control_reply(self._shm_front, msg)
            if reply is not None:
                self._shm_front.send(chan, reply)
                return
            self._handle_client(chan, msg)

        self._shm_front.pump(handle)

    def _drain_replica_shm(self, rep):
        while rep.shm is not None:
            try:
                reply = rep.shm.try_recv()
            except (OSError, EOFError) as exc:
                self._demote_backend(rep, f"{type(exc).__name__}: {exc}")
                return
            if reply is None:
                return
            self._handle_replica_reply(rep, reply)

    def _drain_replica(self, rep):
        import zmq

        drain_socket(
            lambda: wire.recv_message_dealer(rep.sock,
                                             flags=zmq.NOBLOCK),
            lambda reply: self._handle_replica_reply(rep, reply),
            self.counters, "gateway", "replica reply",
        )

    def serve_forever(self, stop_event=None, poll_ms=50):
        import zmq

        poller = zmq.Poller()
        poller.register(self._front, zmq.POLLIN)
        if self._shm_front is not None and self._shm_front.fd is not None:
            # ONE fd wakes the loop for the whole shm side: front
            # channels ding it directly, and the backend channels were
            # attached with it as their reply bell
            poller.register(self._shm_front.fd, zmq.POLLIN)
        for rep in self._replicas.values():
            poller.register(rep.sock, zmq.POLLIN)
        # stored so live resize (_admit_replica/_retire_replica, loop
        # thread only) can register/unregister replica sockets
        self._poller = poller
        while stop_event is None or not stop_event.is_set():
            self._apply_notices()
            self._scrape_tick()
            try:
                events = dict(poller.poll(poll_ms))
                if self._front in events:
                    self._drain_front()
                self._drain_front_shm()
                for rep in list(self._replicas.values()):
                    if rep.sock in events:
                        self._drain_replica(rep)
                    self._drain_replica_shm(rep)
            except zmq.ZMQError:
                return  # a socket closed under us: clean shutdown

    def close(self):
        try:
            self._front.close(0)
        except Exception:  # noqa: BLE001 - shutdown best-effort
            pass
        for rep in self._replicas.values():
            self._demote_backend(rep, "gateway shutdown")
            try:
                rep.sock.close(0)
            except Exception:  # noqa: BLE001
                pass
        if self._shm_front is not None:
            try:
                self._shm_front.close(unlink=True)
            except Exception:  # noqa: BLE001
                pass
            self._shm_front = None


class _LocalGatewayHandle:
    """An in-process gateway (thread) for tests and benchmarks."""

    def __init__(self, gateway, thread, stop):
        self.gateway = gateway
        self.address = gateway.address
        self._thread = thread
        self._stop = stop

    def close(self):
        self._stop.set()
        self._thread.join(timeout=5)
        self.gateway.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


def start_gateway_thread(replicas, *, address="tcp://127.0.0.1:*",
                         counters=None, timer=None, **kwargs):
    """Serve a :class:`ServeGateway` from a daemon thread; returns a
    handle with ``.address``, ``.gateway`` and ``.close()``."""
    gateway = ServeGateway(address, replicas, counters=counters,
                           timer=timer, **kwargs)
    stop = threading.Event()
    thread = threading.Thread(
        target=gateway.serve_forever, kwargs={"stop_event": stop},
        daemon=True, name="bjx-serve-gateway",
    )
    thread.start()
    return _LocalGatewayHandle(gateway, thread, stop)


# ---------------------------------------------------------------------------
# Sharded data plane: N worker processes behind one front address
# ---------------------------------------------------------------------------


#: How many recent control-snapshot mids the front remembers: worker
#: acks for those mids are swallowed instead of treated as client
#: replies.  A handful of versions can be in flight across N workers;
#: 64 is headroom.
SNAPSHOT_MID_DEPTH = 64


class _FrontRoute:
    """One relayed, in-flight request at the sharded front: which
    client to answer and which worker owes the reply."""

    __slots__ = ("ident", "widx", "cmd")

    def __init__(self, ident, widx, cmd):
        self.ident = ident
        self.widx = widx
        self.cmd = cmd


class _Worker:
    """The front's view of one gateway worker process."""

    __slots__ = ("idx", "tag", "address", "sock", "alive", "last_ok",
                 "scrape_mid", "scrape_sent", "next_scrape", "counters")

    def __init__(self, idx, address, sock, now):
        self.idx = idx
        self.tag = f"gw{idx}"
        self.address = address
        self.sock = sock
        self.alive = True
        self.last_ok = now
        self.scrape_mid = None
        self.scrape_sent = 0.0
        self.next_scrape = 0.0
        self.counters = {}


class _GatewayLaunchInfo:
    """The :class:`~blendjax.btt.watchdog.FleetWatchdog` launcher
    contract (``.processes`` + owner's ``respawn``) for the worker
    fleet."""

    def __init__(self, processes, addresses):
        self.processes = processes
        self.addresses = {"GATEWAY_WORKER": addresses}


class ShardedGateway:
    """One client-facing front address over N ``GatewayWorker``
    processes plus the control plane, in one supervising process.

    The split (docs/serving.md, "The sharded gateway"):

    - **data plane**: N worker processes (``python -m
      blendjax.serve.gateway_worker``), each a full :class:`ServeGateway`
      in worker mode with its own client-facing address, its own shm
      front, its own leases and reply cache.  Lease ownership is
      partitioned by the lease id itself — worker k allocates ids
      ≡ k (mod N), so ``owner(ep) = ep % N`` is computable statelessly
      by the front, a client, or a debugger;
    - **front** (this class): binds the ONE address clients dial first.
      It relays a client's first traffic to the owning worker, and every
      successful ``reset`` reply gains a ``gw_workers`` map so the
      client re-dials its owning worker DIRECTLY — steady-state request
      bytes never cross the front again.  Fresh traffic (``reset``,
      unroutable mids) is assigned by ``crc32(mid) % active_workers``
      with a linear probe past dead workers, so a same-mid retry lands
      on the worker whose dedupe/reply cache keeps it exactly-once;
    - **control plane**: an inner :class:`ServeGateway` pointed at the
      replica fleet, pumped from the front's loop.  It alone scrapes
      telemetry, quarantines/re-admits replicas, owns drain flags and
      canary/promote/rollback verdicts and the load-score table.  That
      state reaches workers as a versioned ``gw_snapshot`` publication
      (the WeightBus publish pattern pointed at routing state): workers
      only ever READ a consistent snapshot and never RPC the control
      plane on the request path.

    Workers are supervised by a
    :class:`~blendjax.btt.watchdog.FleetWatchdog` (``restart=True``).
    A SIGKILLed worker takes its leases with it: the front answers
    steps against its partition with the actionable stale-lease error
    (``gateway_lease_rehash``) until the respawn's first answered
    scrape re-admits it (``gateway_worker_respawns``), and clients
    resume after ``reset()`` exactly as for a replica death.  Each
    worker's ``/dev/shm`` segments live under a parent-pinned base
    prefix that is glob-swept before its respawn and at close
    (PR-12 hygiene).
    """

    def __init__(self, address, replicas, *, workers=2,
                 scrape_interval_s=0.25, quarantine_after_s=None,
                 lease_ttl_s=600.0, counters=None, timer=None,
                 context=None, python=None, ready_timeout_s=60.0):
        import zmq

        from blendjax.replay.shard_client import free_port

        if int(workers) < 1:
            raise ValueError("a sharded gateway needs >= 1 worker")
        self.n_workers = int(workers)
        #: fresh-traffic hash window (bench arms shrink it; lease-owned
        #: traffic still reaches workers outside the window)
        self.active_workers = self.n_workers
        self.scrape_interval_s = float(scrape_interval_s)
        self.quarantine_after_s = (
            max(1.0, 4 * self.scrape_interval_s)
            if quarantine_after_s is None else float(quarantine_after_s)
        )
        self.counters = counters if counters is not None else fleet_counters
        self.timer = timer if timer is not None else StageTimer()
        self._ctx = context or zmq.Context.instance()
        self._front = self._ctx.socket(zmq.ROUTER)
        self._front.setsockopt(zmq.LINGER, 0)
        if address.endswith(":*") or address.endswith(":0"):
            base = address.rsplit(":", 1)[0]
            port = self._front.bind_to_random_port(base)
            self.address = f"{base}:{port}"
        else:
            self._front.bind(address)
            self.address = address
        #: the control plane: a standalone ServeGateway over the replica
        #: fleet, pumped from THIS loop.  Its client front is an unused
        #: ephemeral port; what we want is its scrape/quarantine/canary
        #: machinery and its replica table — the gw_snapshot source.
        self._ctl = ServeGateway(
            "tcp://127.0.0.1:*", replicas,
            scrape_interval_s=self.scrape_interval_s,
            quarantine_after_s=quarantine_after_s,
            lease_ttl_s=None, counters=self.counters, timer=self.timer,
            context=self._ctx, enable_shm=False,
        )
        self.python = python or sys.executable
        self.ready_timeout_s = float(ready_timeout_s)
        now = time.monotonic()
        self._workers = []
        self._wcmds = []
        #: parent-pinned shm base prefix per worker: respawns reuse the
        #: name, and the parent glob-sweeps it before each respawn and
        #: at close, so a SIGKILLed worker cannot leak /dev/shm
        self._wbases = []
        for k in range(self.n_workers):
            waddr = f"tcp://127.0.0.1:{free_port()}"
            base = (shm_rpc.new_base(f"gww{k}")
                    if shm_rpc.enabled() else None)
            cmd = [self.python, "-m", "blendjax.serve.gateway_worker",
                   "--address", waddr,
                   "--worker-index", str(k),
                   "--workers", str(self.n_workers),
                   "--scrape-interval", str(self.scrape_interval_s)]
            if lease_ttl_s is not None:
                cmd += ["--lease-ttl", str(float(lease_ttl_s))]
            for addr in replicas:
                cmd += ["--replica", addr]
            if base is not None:
                cmd += ["--shm-base", base]
            sock = self._ctx.socket(zmq.DEALER)
            sock.setsockopt(zmq.LINGER, 0)
            sock.connect(waddr)
            self._workers.append(_Worker(k, waddr, sock, now))
            self._wcmds.append(cmd)
            self._wbases.append(base)
        self._routes = OrderedDict()   # mid -> _FrontRoute
        self._wscrapes = {}            # mid -> worker idx
        self._snap_mids = deque(maxlen=SNAPSHOT_MID_DEPTH)
        self._snap_version = -1
        self._next_publish = 0.0
        self._notices = deque()
        self.launch_info = None

    # -- worker process management -------------------------------------------

    def _spawn(self, idx):
        from blendjax.btt.launcher import child_env

        env = child_env()
        env.setdefault("JAX_PLATFORMS", "cpu")
        env.pop("PALLAS_AXON_POOL_IPS", None)
        return subprocess.Popen(self._wcmds[idx], env=env,
                                start_new_session=True)

    def start(self):
        procs = []
        try:
            for k in range(self.n_workers):
                procs.append(self._spawn(k))
            self.launch_info = _GatewayLaunchInfo(
                procs, [w.address for w in self._workers])
            self._wait_ready()
        except BaseException:
            if self.launch_info is None:
                self.launch_info = _GatewayLaunchInfo(procs, [])
            self.close()
            raise
        return self

    def _wait_ready(self):
        from blendjax.serve.client import ServeClient

        deadline = time.monotonic() + self.ready_timeout_s
        for w in self._workers:
            while True:
                if time.monotonic() > deadline:
                    raise TimeoutError(
                        f"gateway worker {w.tag} at {w.address} not "
                        f"ready within {self.ready_timeout_s:.1f}s"
                    )
                probe = ServeClient(w.address, timeoutms=500, shm=False,
                                    follow_redirects=False)
                try:
                    probe.hello()
                    break
                except TimeoutError:
                    continue
                finally:
                    probe.close()

    def respawn(self, idx):
        """FleetWatchdog's restart hook: sweep the dead worker's shm
        base first (PR-12 hygiene), then relaunch the SAME command —
        address, index and base prefix are parent-pinned, so the
        respawn rejoins under its old identity."""
        if self._wbases[idx] is not None:
            shm_rpc.unlink_base(self._wbases[idx])
        proc = self._spawn(idx)
        self.launch_info.processes[idx] = proc
        return proc

    # -- admin (thread-safe flag sets on the control plane; workers
    # -- learn of them from the next published snapshot) ---------------------

    def drain(self, rid):
        return self._ctl.drain(rid)

    def undrain(self, rid):
        return self._ctl.undrain(rid)

    def canary(self, version, fraction=0.25):
        return self._ctl.canary(version, fraction)

    def promote(self):
        return self._ctl.promote()

    def rollback(self):
        return self._ctl.rollback()

    # -- watchdog notices (thread-safe; applied on the loop) -----------------

    def notify_worker_death(self, idx, exit_code=None):
        self._notices.append(("death", int(idx)))

    def notify_worker_respawn(self, idx, proc=None):
        self._notices.append(("respawn", int(idx)))

    def notify_replica_death(self, idx_or_rid, exit_code=None):
        self._ctl.notify_replica_death(idx_or_rid, exit_code)

    def notify_replica_respawn(self, idx_or_rid, proc=None):
        self._ctl.notify_replica_respawn(idx_or_rid, proc)

    def _apply_notices(self):
        while self._notices:
            kind, idx = self._notices.popleft()
            w = self._workers[idx]
            if kind == "death":
                self._mark_worker_dead(w)
            else:
                # probe the respawn immediately: its first answered
                # scrape re-admits it
                w.next_scrape = 0.0

    def _mark_worker_dead(self, w):
        if not w.alive:
            return
        w.alive = False
        if w.scrape_mid is not None:
            self._wscrapes.pop(w.scrape_mid, None)
            w.scrape_mid = None
        self.counters.incr("gateway_worker_deaths")
        logger.warning(
            "gateway front: worker %s at %s is gone — its lease "
            "partition (ep %% %d == %d) is stale until respawn",
            w.tag, w.address, self.n_workers, w.idx,
        )

    def set_active_workers(self, n):
        """Restrict FRESH-traffic hash assignment (and the
        ``gw_workers`` redirect map) to the first ``n`` workers.  A
        bench knob: the 1-worker and N-worker arms run over the same
        fleet and the same worker processes.  Lease-owned traffic
        still reaches its owning worker.

        ``n == 1`` collapses the data plane to the UNSHARDED shape:
        the front withholds the direct-dial map, so every message —
        fresh and lease-owned alike — rides this one front address
        through one event loop, exactly what a monolithic gateway
        deployment looks like to clients.  That is the baseline arm
        of ``gateway_shard_x``; ``n > 1`` restores partitioned
        direct dial."""
        self.active_workers = max(1, min(int(n), self.n_workers))
        return self.active_workers

    # -- worker health + control snapshots -----------------------------------

    def _worker_tick(self):
        import zmq

        now = time.monotonic()
        for w in self._workers:
            if (w.scrape_mid is not None
                    and now - w.scrape_sent > 2 * self.scrape_interval_s):
                self._wscrapes.pop(w.scrape_mid, None)
                w.scrape_mid = None
            if w.scrape_mid is None and now >= w.next_scrape:
                msg = {"cmd": "telemetry"}
                mid = wire.stamp_message_id(msg)
                try:
                    wire.send_message_dealer(w.sock, msg,
                                             flags=zmq.DONTWAIT)
                except zmq.ZMQError:
                    continue
                w.scrape_mid = mid
                w.scrape_sent = now
                w.next_scrape = now + self.scrape_interval_s
                self._wscrapes[mid] = w.idx
            if w.alive and now - w.last_ok > self.quarantine_after_s:
                self._mark_worker_dead(w)

    def _ingest_worker_scrape(self, w, reply):
        w.scrape_mid = None
        if not w.alive:
            w.alive = True
            self.counters.incr("gateway_worker_respawns")
            logger.warning(
                "gateway front: worker %s answered again — re-admitted",
                w.tag,
            )
            # a fresh worker starts with an empty routing view: publish
            # the current control state before client traffic reaches it
            self._publish_snapshot(force=True)
        counters = reply.get("counters")
        if isinstance(counters, dict):
            w.counters = counters

    def _publish_snapshot(self, force=False):
        """Version and fan the control plane's routing state out to the
        workers (replica health/drain/load + canary verdicts).  Workers
        apply it atomically under their GIL; stale versions are
        ignored, so a re-ordered publish can never roll a worker's view
        backwards."""
        import zmq

        now = time.monotonic()
        if not force and now < self._next_publish:
            return
        self._next_publish = now + self.scrape_interval_s
        ctl = self._ctl
        self._snap_version += 1
        msg = {
            "cmd": "gw_snapshot",
            "version": self._snap_version,
            "replicas": {
                rep.id: {
                    "healthy": rep.healthy,
                    "draining": rep.draining,
                    "models": sorted(rep.models or ()),
                    "queued": rep.queued,
                    "live": rep.live,
                    "p99_ms": rep.p99_ms,
                    "pid": rep.pid,
                    "incarnation": rep.incarnation,
                    "weight_version": rep.weight_version,
                    "caps": rep.caps,
                }
                for rep in ctl._replicas.values()
            },
            "weights": {
                "canary_version": ctl._canary_version,
                "canary_fraction": ctl._canary_fraction,
                "stable_version": ctl._stable_version,
                "rejected_version": ctl._rejected_version,
            },
        }
        mid = wire.stamp_message_id(msg)
        self._snap_mids.append(mid)
        sent = 0
        for w in self._workers:
            if not w.alive:
                continue
            try:
                wire.send_message_dealer(w.sock, msg, flags=zmq.DONTWAIT)
                sent += 1
            except zmq.ZMQError:
                continue
        if sent:
            self.counters.incr("gateway_snapshot_publishes")

    # -- front request handling ----------------------------------------------

    def _worker_map(self):
        """tag -> direct-dial address for the live workers in the
        active window — what a successful ``reset`` reply carries so
        the client's steady-state traffic skips this front."""
        return {w.tag: w.address
                for w in self._workers[:self.active_workers] if w.alive}

    def _sharded_fields(self):
        return {
            "gateway": True,
            "sharded": True,
            "workers": self.n_workers,
            "active_workers": self.active_workers,
            "workers_alive": sum(1 for w in self._workers if w.alive),
            "gw_workers": self._worker_map(),
            "gw_n_workers": self.n_workers,
            "pid": os.getpid(),
        }

    def gateway_counters(self):
        """``gateway_*`` counters merged across the front process and
        every worker's latest scrape — the fleet-wide view ``stats``
        and ``telemetry`` answer with."""
        out = dict(self.counters.snapshot())
        for w in self._workers:
            for key, val in (w.counters or {}).items():
                if key.startswith("gateway_") or key == "stale_replies":
                    out[key] = out.get(key, 0) + val
        return out

    def _pick_worker_for_mid(self, mid):
        """Deterministic fresh-traffic assignment: crc32 of the
        correlation id over the active window (NOT ``hash()`` — that is
        salted per process), linear-probed past dead workers so a
        same-mid retry lands on the same worker whenever that worker is
        up (its dedupe/reply cache keeps the retry exactly-once)."""
        n = max(1, min(self.active_workers, len(self._workers)))
        start = zlib.crc32(str(mid).encode()) % n
        for k in range(n):
            w = self._workers[(start + k) % n]
            if w.alive:
                return w
        return None

    def _front_reply(self, ident, msg, reply, *, span_name):
        """Answer a request from the front itself.  No reply cache:
        every front-local answer is a pure function of (request,
        current worker liveness), so a same-mid retry recomputes the
        same answer."""
        import zmq

        mid = msg.get(wire.BTMID_KEY)
        if "error" in reply:
            self.counters.incr("gateway_errors")
        span_ctx = msg.get(wire.SPAN_KEY)
        if isinstance(span_ctx, dict) and span_ctx.get("trace") is not None:
            reply = dict(reply)
            reply[wire.SPANS_KEY] = [make_span(
                span_name, now_us(), trace=span_ctx["trace"],
                cat="gateway",
            )]
        if mid is not None:
            reply[wire.BTMID_KEY] = mid
        try:
            wire.send_message_router(self._front, ident, reply,
                                     raw_buffers=True)
            self.counters.incr("gateway_replies")
        except zmq.ZMQError:
            pass  # client gone; its retry will re-dial

    def _resolve(self, msg):
        """``gw_resolve``: map an episode lease to its owning worker.
        The recovery path for a client that direct-dialed a worker that
        died — it asks the front where to go next."""
        ep = msg.get("episode")
        try:
            widx = int(ep) % self.n_workers
        except (TypeError, ValueError):
            return {"error": (
                f"gw_resolve needs an integer episode lease, got {ep!r}"
            ), "gw_workers": self._worker_map()}
        w = self._workers[widx]
        return {"gw_worker": w.tag, "address": w.address,
                "alive": w.alive, "gw_workers": self._worker_map()}

    def _handle_front_client(self, ident, msg):
        import zmq

        mid = msg.get(wire.BTMID_KEY)
        cmd = msg.get("cmd")
        # the front is pure ZMQ: shm negotiation gets the standard
        # refusal (clients mark the channel off and, after redirecting
        # to their worker's address, re-arm and negotiate THERE)
        reply = shm_rpc.control_reply(None, msg)
        if reply is not None:
            try:
                wire.send_message_router(self._front, ident, reply)
            except zmq.ZMQError:
                pass
            return
        if cmd == "gw_resolve":
            self.counters.incr("gateway_requests")
            self._front_reply(ident, msg, self._resolve(msg),
                              span_name="gateway:gw_resolve")
            return
        if cmd == "hello":
            self.counters.incr("gateway_requests")
            out = self._ctl._cmd_hello(msg)
            if "obs_dim" not in out and mid is not None:
                # the control plane has not scraped capabilities yet
                # (startup): relay through a worker, which forwards to
                # a replica; the reply path overlays the sharded fields
                w = self._pick_worker_for_mid(mid)
                if w is not None:
                    self._relay_to(w, ident, msg, cmd)
                    return
            out.update(self._sharded_fields())
            self._front_reply(ident, msg, out, span_name="gateway:hello")
            return
        if cmd in ("drain", "undrain", "canary", "promote", "rollback"):
            self.counters.incr("gateway_requests")
            handler = getattr(self._ctl, f"_cmd_{cmd}")
            try:
                out = handler(msg)
            except Exception as exc:  # noqa: BLE001 - report, don't die
                logger.exception("gateway front: %s failed", cmd)
                out = {"error": f"{type(exc).__name__}: {exc}"}
            # admin verdicts must not wait a scrape interval to reach
            # the data plane
            self._publish_snapshot(force=True)
            self._front_reply(ident, msg, out,
                              span_name=f"gateway:{cmd}")
            return
        if cmd == "stats":
            self.counters.incr("gateway_requests")
            self._front_reply(ident, msg, self._cmd_stats(msg),
                              span_name="gateway:stats")
            return
        if cmd == "telemetry":
            self.counters.incr("gateway_requests")
            self._front_reply(ident, msg, self._cmd_telemetry(msg),
                              span_name="gateway:telemetry")
            return
        self._relay(ident, msg, cmd, mid)

    def _cmd_stats(self, msg):
        out = self._ctl._cmd_stats(msg)
        out.update(self._sharded_fields())
        out["counters"] = self.gateway_counters()
        return out

    def _cmd_telemetry(self, msg):
        out = self._ctl._cmd_telemetry(msg)
        out.update(self._sharded_fields())
        out["counters"] = self.gateway_counters()
        return out

    def _relay(self, ident, msg, cmd, mid):
        if mid is None:
            self.counters.incr("gateway_requests")
            self._front_reply(ident, msg, {"error": (
                f"{cmd!r} through a gateway needs a correlation id "
                "(wire.stamp_message_id); its reply could not be "
                "routed back otherwise"
            )}, span_name=f"gateway:{cmd}")
            return
        route = self._routes.get(mid)
        if route is not None:
            # a retry of an in-flight relay: same worker (its dedupe /
            # reply cache keeps it exactly-once) as long as it lives
            w = self._workers[route.widx]
            if w.alive:
                route.ident = ident
                self._relay_to(w, ident, msg, cmd, record=False)
                return
            del self._routes[mid]
        if cmd in ("step", "close"):
            ep = msg.get("episode")
            widx = None
            try:
                widx = int(ep) % self.n_workers
            except (TypeError, ValueError):
                pass  # unintelligible lease: any live worker rejects it
            if widx is not None:
                w = self._workers[widx]
                if not w.alive:
                    # the owning worker died and took the lease's
                    # reply cache / replica route with it — the lease
                    # is unrecoverable, exactly like a replica death
                    self.counters.incr("gateway_requests")
                    self.counters.incr("gateway_lease_rehash")
                    self.counters.incr("gateway_stale_lease_redirects")
                    if cmd == "close":
                        self._front_reply(ident, msg, {"closed": False},
                                          span_name="gateway:close")
                    else:
                        self._front_reply(ident, msg, {"error": (
                            f"stale episode lease {ep!r} (gateway "
                            f"worker {w.tag} died): reset() and resume "
                            "on a healthy replica"
                        ), "lease": "stale"}, span_name="gateway:step")
                    return
                self._relay_to(w, ident, msg, cmd)
                return
        w = self._pick_worker_for_mid(mid)
        if w is None:
            self.counters.incr("gateway_requests")
            self._front_reply(ident, msg, {"error": (
                "no live gateway worker (of "
                f"{[x.tag for x in self._workers]}): retry after the "
                "watchdog respawns one"
            )}, span_name=f"gateway:{cmd}")
            return
        self._relay_to(w, ident, msg, cmd)

    def _relay_to(self, w, ident, msg, cmd, record=True):
        import zmq

        mid = msg.get(wire.BTMID_KEY)
        if record and mid is not None:
            self._routes[mid] = _FrontRoute(ident, w.idx, cmd)
            while len(self._routes) > ROUTE_CACHE_DEPTH:
                self._routes.popitem(last=False)
        try:
            wire.send_message_dealer(w.sock, msg, raw_buffers=True,
                                     flags=zmq.DONTWAIT)
        except zmq.Again:
            if mid is not None:
                self._routes.pop(mid, None)
            self.counters.incr("gateway_requests")
            self._front_reply(ident, msg, {"error": (
                f"gateway worker {w.tag} send queue full (stalled or "
                "unreachable): retry, or reset() after its respawn"
            )}, span_name=f"gateway:{cmd}")
            return
        except zmq.ZMQError:
            if mid is not None:
                self._routes.pop(mid, None)
            return
        self.counters.incr("gateway_front_relays")

    def _handle_worker_reply(self, w, reply):
        w.last_ok = time.monotonic()
        mid = reply.get(wire.BTMID_KEY)
        if mid is not None and mid in self._wscrapes:
            del self._wscrapes[mid]
            self._ingest_worker_scrape(w, reply)
            return
        if mid is not None and mid in self._snap_mids:
            return  # snapshot ack
        route = self._routes.pop(mid, None) if mid is not None else None
        if route is None:
            self.counters.incr("stale_replies")
            return
        if (route.cmd == "reset" and "error" not in reply
                and self.active_workers > 1):
            # the redirect payload: the client moves its channel to its
            # owning worker's own address and never relays here again.
            # With the data plane collapsed to one worker the map is
            # withheld — every message keeps riding this front, which
            # IS the unsharded single-address shape the shard bench
            # arm measures against.
            reply["gw_workers"] = self._worker_map()
            reply["gw_n_workers"] = self.n_workers
        elif route.cmd == "hello":
            fields = self._sharded_fields()
            if self.active_workers == 1:
                fields.pop("gw_workers", None)
            reply.update(fields)
        import zmq

        try:
            wire.send_message_router(self._front, route.ident, reply,
                                     raw_buffers=True)
            self.counters.incr("gateway_replies")
        except zmq.ZMQError:
            pass

    # -- loop ----------------------------------------------------------------

    def _drain_front(self):
        import zmq

        drain_socket(
            lambda: wire.recv_message_router(self._front,
                                             flags=zmq.NOBLOCK),
            lambda out: self._handle_front_client(out[0], out[1]),
            self.counters, "gateway front", "client request",
        )

    def _drain_worker(self, w):
        import zmq

        drain_socket(
            lambda: wire.recv_message_dealer(w.sock, flags=zmq.NOBLOCK),
            lambda reply: self._handle_worker_reply(w, reply),
            self.counters, "gateway front", "worker reply",
        )

    def serve_forever(self, stop_event=None, poll_ms=50):
        import zmq

        poller = zmq.Poller()
        poller.register(self._front, zmq.POLLIN)
        for w in self._workers:
            poller.register(w.sock, zmq.POLLIN)
        for rep in self._ctl._replicas.values():
            poller.register(rep.sock, zmq.POLLIN)
        while stop_event is None or not stop_event.is_set():
            self._apply_notices()
            self._ctl._apply_notices()
            self._ctl._scrape_tick()
            self._worker_tick()
            self._publish_snapshot()
            try:
                events = dict(poller.poll(poll_ms))
                if self._front in events:
                    self._drain_front()
                for w in self._workers:
                    if w.sock in events:
                        self._drain_worker(w)
                for rep in self._ctl._replicas.values():
                    if rep.sock in events:
                        self._ctl._drain_replica(rep)
            except zmq.ZMQError:
                return  # a socket closed under us: clean shutdown

    def close(self):
        try:
            self._front.close(0)
        except Exception:  # noqa: BLE001 - shutdown best-effort
            pass
        for w in self._workers:
            try:
                w.sock.close(0)
            except Exception:  # noqa: BLE001
                pass
        info = self.launch_info
        if info is not None:
            for proc in info.processes:
                try:
                    proc.terminate()
                except Exception:  # noqa: BLE001
                    pass
            for proc in info.processes:
                try:
                    proc.wait(timeout=5)
                except Exception:  # noqa: BLE001
                    try:
                        proc.kill()
                        proc.wait(timeout=5)
                    except Exception:  # noqa: BLE001
                        pass
        for base in self._wbases:
            if base is not None:
                shm_rpc.unlink_base(base)
        self._ctl.close()

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.close()
        return False


class _LocalShardedHandle:
    """An in-process sharded-gateway front (thread) plus its worker
    processes and watchdog, for tests and benchmarks."""

    def __init__(self, gateway, thread, stop, watchdog):
        self.gateway = gateway
        self.address = gateway.address
        self._thread = thread
        self._stop = stop
        self._watchdog = watchdog

    def set_active_workers(self, n):
        return self.gateway.set_active_workers(n)

    def close(self):
        if self._watchdog is not None:
            self._watchdog.stop()
        self._stop.set()
        self._thread.join(timeout=5)
        self.gateway.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


def start_sharded_gateway_thread(replicas, *, address="tcp://127.0.0.1:*",
                                 workers=2, counters=None, timer=None,
                                 supervise=True, watchdog_interval_s=0.2,
                                 **kwargs):
    """Spawn N gateway worker processes + the front/control loop in a
    daemon thread, supervised by a FleetWatchdog (``restart=True``);
    returns a handle with ``.address``, ``.gateway``,
    ``.set_active_workers()`` and ``.close()``."""
    gateway = ShardedGateway(address, replicas, workers=workers,
                             counters=counters, timer=timer,
                             **kwargs).start()
    stop = threading.Event()
    thread = threading.Thread(
        target=gateway.serve_forever, kwargs={"stop_event": stop},
        daemon=True, name="bjx-sharded-gateway",
    )
    thread.start()
    watchdog = None
    if supervise:
        from blendjax.btt.watchdog import FleetWatchdog

        watchdog = FleetWatchdog(
            gateway, interval=watchdog_interval_s, restart=True,
            on_death=gateway.notify_worker_death,
            on_respawn=gateway.notify_worker_respawn,
        )
        watchdog.start()
    return _LocalShardedHandle(gateway, thread, stop, watchdog)


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="Route a fleet of blendjax policy servers."
    )
    ap.add_argument("--address", required=True)
    ap.add_argument("--replica", action="append", required=True,
                    help="backend replica address (repeatable)")
    ap.add_argument("--scrape-interval", type=float, default=0.25)
    args = ap.parse_args(argv)
    logging.basicConfig(level=logging.INFO)
    gateway = ServeGateway(args.address, args.replica,
                           scrape_interval_s=args.scrape_interval)
    stop = threading.Event()

    def _term(signum, frame):
        stop.set()

    signal.signal(signal.SIGTERM, _term)
    signal.signal(signal.SIGINT, _term)
    logger.info("serve gateway at %s over %d replicas",
                gateway.address, len(args.replica))
    try:
        gateway.serve_forever(stop_event=stop)
    finally:
        gateway.close()


if __name__ == "__main__":
    main()
