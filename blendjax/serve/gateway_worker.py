"""GatewayWorker: one shard of the sharded gateway data plane.

``python -m blendjax.serve.gateway_worker`` runs a
:class:`~blendjax.serve.gateway.ServeGateway` in **worker mode**
(``worker_index`` of ``n_workers``): a full gateway — its own client
address, its own shm front, leases, reply cache, replica DEALERs — with
two deliberate amputations that make N of them safe behind one front:

- it allocates lease ids congruent to ``worker_index`` mod
  ``n_workers`` (never colliding with a sibling, owner computable from
  the id alone), and
- it does NOT scrape, quarantine or canary the replica fleet.  That is
  the control plane's job (:class:`~blendjax.serve.gateway.
  ShardedGateway`); its verdicts arrive as versioned ``gw_snapshot``
  publications the worker applies atomically — the request path reads a
  consistent local view and never RPCs anyone about routing state.

Workers are spawned, supervised (FleetWatchdog, ``restart=True``) and
shm-swept by the :class:`~blendjax.serve.gateway.ShardedGateway` front;
running one standalone is only useful for debugging a single shard.
See docs/serving.md ("The sharded gateway").
"""

from __future__ import annotations

import argparse
import logging
import signal
import threading

from blendjax.serve.gateway import ServeGateway

logger = logging.getLogger("blendjax")


class GatewayWorker(ServeGateway):
    """A worker-mode :class:`ServeGateway` (see module docstring).
    Construction requires the shard identity; everything else is the
    plain gateway."""

    def __init__(self, address, replicas, *, worker_index, n_workers,
                 **kwargs):
        if worker_index is None:
            raise ValueError("a GatewayWorker needs worker_index")
        if not 0 <= int(worker_index) < int(n_workers):
            raise ValueError(
                f"worker_index {worker_index} out of range for "
                f"{n_workers} workers"
            )
        super().__init__(address, replicas, worker_index=int(worker_index),
                         n_workers=int(n_workers), **kwargs)


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="One shard of a sharded blendjax serve gateway."
    )
    ap.add_argument("--address", required=True,
                    help="this worker's own client-facing address")
    ap.add_argument("--replica", action="append", required=True,
                    help="backend replica address (repeatable)")
    ap.add_argument("--worker-index", type=int, required=True)
    ap.add_argument("--workers", type=int, required=True,
                    help="total workers in the shard set")
    ap.add_argument("--scrape-interval", type=float, default=0.25)
    ap.add_argument("--lease-ttl", type=float, default=600.0)
    ap.add_argument("--shm-base", default=None,
                    help="parent-pinned shm base prefix (the front "
                         "sweeps it around respawns)")
    args = ap.parse_args(argv)
    logging.basicConfig(level=logging.INFO)
    worker = GatewayWorker(
        args.address, args.replica,
        worker_index=args.worker_index, n_workers=args.workers,
        scrape_interval_s=args.scrape_interval,
        lease_ttl_s=args.lease_ttl, shm_base=args.shm_base,
    )
    stop = threading.Event()

    def _term(signum, frame):
        stop.set()

    signal.signal(signal.SIGTERM, _term)
    signal.signal(signal.SIGINT, _term)
    logger.info("gateway worker gw%d/%d at %s over %d replicas",
                args.worker_index, args.workers, worker.address,
                len(args.replica))
    try:
        worker.serve_forever(stop_event=stop)
    finally:
        worker.close()


if __name__ == "__main__":
    main()
