"""ServeClient: one episode's blocking RPC channel to a PolicyServer.

The consumer half of the serving tier (docs/serving.md): a DEALER
socket speaking the empty-delimiter framing from :mod:`blendjax.wire`,
every RPC stamped with a ``wire.BTMID_KEY`` correlation id and run
under a :class:`~blendjax.btt.faults.FaultPolicy` — a retry re-sends
the SAME id, the server's reply cache answers it without a second
decode, and replies whose id does not match the outstanding request are
dropped as stale (the ``ShardClient`` discipline pointed at inference).

Episode protocol::

    client = ServeClient("tcp://host:24000")
    slot = client.reset()            # admit an episode (KV-cache slot)
    for obs in episode:
        pred = client.step(obs)      # one batched-on-the-server decode
    client.close_episode()           # release the slot

A step against a restarted server (fresh slot pool) raises
``RuntimeError`` naming the unknown slot; call :meth:`reset` and
resume — the recovery path the chaos tests exercise under
``FleetWatchdog`` respawns.
"""

from __future__ import annotations

import logging
import random
import time

import numpy as np

from blendjax.btt.faults import FaultPolicy
from blendjax.utils.timing import fleet_counters

logger = logging.getLogger("blendjax")


class ServeRPCError(TimeoutError):
    """A serve RPC failed at the transport level (no reply within the
    policy, circuit open).  Subclasses :class:`TimeoutError` so callers
    that treat outages as retriable-later (reset-and-resume loops)
    handle them uniformly."""


class ServeClient:
    """Blocking exactly-once RPCs to one :class:`~blendjax.serve.server.
    PolicyServer` (ROUTER/batched or REP/serial — the DEALER framing
    serves both unmodified)."""

    def __init__(self, address, *, fault_policy=None, counters=None,
                 timeoutms=5000, context=None, span_recorder=None,
                 name="serve", model=None, shm="auto", shm_chaos=None,
                 follow_redirects=True, fallback_backoff_s=0.05,
                 fallback_backoff_max_s=2.0):
        self.address = address
        #: the address this client was CONSTRUCTED with — against a
        #: sharded gateway that is the front, and the recovery anchor:
        #: when a direct-dialed worker dies, the client falls back here
        #: so the next RPC re-resolves (see rpc())
        self._front_address = address
        #: follow a sharded front's ``gw_workers`` handoff (reset
        #: replies name the worker owning the new lease; the client
        #: re-points its channel at that worker's own address so
        #: steady-state traffic never crosses the front again).
        #: ``False`` pins every request to the constructed address —
        #: chaos proxies and probes that must see one fixed peer.
        self.follow_redirects = bool(follow_redirects)
        self.name = name
        self.policy = fault_policy or FaultPolicy()
        self.state = self.policy.new_state()
        self.counters = counters if counters is not None else fleet_counters
        self.timeoutms = int(timeoutms)
        self.slot = None  # the live episode's slot after reset()
        self.episode = None  # ... and its lease id (see reset())
        #: model id this client's episodes run on (multi-model servers
        #: / gateway routing); None = the server's default model
        self.model = model
        #: the replica id that served the LAST reply (stamped by a
        #: ServeGateway; None against a bare server) — surfaced in
        #: ServeRPCError text and span args so a misbehaving replica is
        #: diagnosable from a client traceback alone
        self.replica = None
        #: the gateway WORKER that served the last reply (stamped in
        #: worker mode; None against a bare server or a plain gateway)
        #: — the sharded analog of the replica stamp
        self.gw_worker = None
        #: the WeightBus version that served the LAST reply (stamped by
        #: subscribed servers; None against a bus-less server) —
        #: surfaced alongside the replica stamp, so a bad-version
        #: rollout is diagnosable from a client traceback alone
        self.weight_version = None
        #: cross-process span sink (None = tracing off): client RPC
        #: spans plus the server's piggybacked serve-side spans
        self.spans = span_recorder
        self._ctx = context
        self._shm_mode = shm
        self._shm_chaos = shm_chaos
        self._chan = None
        #: front-fallback pacing: consecutive transport failures since
        #: the last good reply.  Each failure that re-points at the
        #: front first sleeps ``min(max, base * 2**(n-1))`` with
        #: uniform jitter, so a worker-respawn window is not a tight
        #: re-dial loop bursting load onto the relay front.  ``base=0``
        #: disables the pause (latency-critical probes).
        self._fallback_failures = 0
        self._fallback_backoff_s = float(fallback_backoff_s)
        self._fallback_backoff_max_s = float(fallback_backoff_max_s)

    def _channel(self):
        if self._chan is None:
            from blendjax.btt.transport import RpcChannel

            self._chan = RpcChannel(
                self.address, context=self._ctx, shm=self._shm_mode,
                shm_chaos=self._shm_chaos, name=self.name,
            )
        return self._chan

    @property
    def transport(self):
        """The wire the next RPC rides: ``"shm"`` or ``"tcp"``."""
        return self._chan.transport if self._chan is not None else "tcp"

    def reset_channel(self):
        """Drop the channel (DEALER socket AND any shm ring pair) so
        the next RPC dials fresh (stale replies of a dead server
        incarnation die with the old one)."""
        if self._chan is not None:
            self._chan.reset()

    close = reset_channel

    def rpc(self, cmd, payload=None, *, timeout_ms=None,
            raw_buffers=False):
        """One exactly-once RPC under the fault policy; returns the
        decoded reply dict.  Raises :class:`ServeRPCError` (transport)
        or ``RuntimeError`` (the server executed and reported failure).
        The retry/stale-reply discipline is the shared
        :func:`blendjax.btt.rpc.exactly_once_rpc`."""
        from blendjax.btt.rpc import exactly_once_rpc

        msg = dict(payload or {})
        msg["cmd"] = cmd
        # the last replica (gateway-stamped) and weight version
        # (bus-stamped) that answered ride the transport-error text and
        # the client span: when a fleet or a rollout misbehaves, the
        # traceback names the suspect replica AND the suspect version
        via = (f", last replica {self.replica}"
               if self.replica is not None else "")
        if self.gw_worker is not None:
            via += f", gateway worker {self.gw_worker}"
        if self.weight_version is not None:
            via += f", weights v{self.weight_version}"
        span_args = {}
        if self.replica is not None:
            span_args["replica"] = self.replica
        if self.gw_worker is not None:
            span_args["gw_worker"] = self.gw_worker
        if self.weight_version is not None:
            span_args["weight_version"] = self.weight_version
        try:
            reply = exactly_once_rpc(
                self._channel, msg,
                policy=self.policy, state=self.state,
                counters=self.counters,
                wait_ms=(self.timeoutms if timeout_ms is None
                         else int(timeout_ms)),
                raw_buffers=raw_buffers, spans=self.spans,
                remote_name="policy server",
                span_label="serve_rpc", span_cat="serve_client",
                span_args=span_args or None,
                rpc_name=f"{self.name}:{cmd}",
                exc_factory=lambda text: ServeRPCError(
                    f"policy server ({self.address}{via}): {text}"
                ),
                retryable=(ServeRPCError,),
                pop_mid=True,
            )
        except ServeRPCError:
            if self.follow_redirects and self.address != self._front_address:
                # the direct-dialed gateway worker went silent: fall
                # back to the front so the NEXT rpc re-resolves (the
                # front answers, relays to a live worker, or names the
                # stale lease) — the raised error already carries the
                # dead worker's id in its text.  The fall-back is
                # PACED: bounded exponential backoff + jitter, so N
                # clients losing the same worker (a respawn window) do
                # not re-dial the front in a lockstep burst
                self._fallback_failures += 1
                delay = self._fallback_delay()
                logger.warning(
                    "%s: gateway worker %s at %s unresponsive; falling "
                    "back to the front at %s (after %.3fs backoff)",
                    self.name, self.gw_worker, self.address,
                    self._front_address, delay,
                )
                if delay > 0:
                    time.sleep(delay)
                self._channel().redirect(self._front_address)
                self.address = self._front_address
            else:
                self._fallback_failures += 1
            raise
        self._fallback_failures = 0
        rep = reply.get("replica")
        if rep is not None:
            self.replica = rep
        gw = reply.get("gw_worker")
        if gw is not None:
            self.gw_worker = gw
        wv = reply.get("weight_version")
        if wv is not None:
            self.weight_version = wv
        self._maybe_follow(reply)
        return reply

    def _fallback_delay(self):
        """The paced re-dial delay for the CURRENT consecutive-failure
        count: ``min(cap, base * 2**(n-1))``, jittered to 50–100% so
        concurrent clients de-correlate."""
        if self._fallback_backoff_s <= 0 or self._fallback_failures <= 0:
            return 0.0
        raw = self._fallback_backoff_s * (
            2.0 ** (self._fallback_failures - 1))
        return min(self._fallback_backoff_max_s, raw) * random.uniform(
            0.5, 1.0)

    def _maybe_follow(self, reply):
        """A sharded front's handoff: a reply naming both the worker
        that answered (``gw_worker``) and the live worker address map
        (``gw_workers``) moves this client's channel onto that worker's
        own address — steady-state traffic skips the front entirely."""
        if not self.follow_redirects:
            return
        gwmap = reply.get("gw_workers")
        tag = reply.get("gw_worker")
        if not isinstance(gwmap, dict) or tag is None:
            return
        target = gwmap.get(tag)
        if target is None or target == self.address:
            return
        self._channel().redirect(target)
        self.address = target

    # -- episode protocol ----------------------------------------------------

    def hello(self, timeout_ms=None):
        return self.rpc("hello", timeout_ms=timeout_ms)

    def _model_payload(self, payload):
        if self.model is not None:
            payload["model"] = self.model
        return payload

    def reset(self, prefix=None, timeout_ms=None, scenario=None):
        """Admit an episode: returns (and remembers) its slot id.  The
        reply's episode *lease* id rides every later step/close, so a
        slot the server evicted and reassigned refuses this client's
        stale steps instead of advancing the new tenant's cache.

        ``prefix`` — a ``(T, obs_dim)`` observation prefix — admits the
        episode MID-SEQUENCE: the server replays it in one
        teacher-forced batched pass (not T serial decodes) and the full
        reply dict is returned instead of the slot, with ``pred`` (the
        prediction for position T) and ``pos`` (the position the next
        ``step`` consumes).

        ``scenario`` — an optional traffic label (docs/scenarios.md):
        rides the admission request, and a fronting
        :class:`~blendjax.serve.gateway.ServeGateway` attributes the
        whole episode's requests/latencies to it in its per-scenario
        records (bare servers ignore it)."""
        payload = self._model_payload({})
        if prefix is not None:
            payload["prefix"] = np.asarray(prefix, np.float32)
        if scenario is not None:
            payload["scenario"] = str(scenario)
        reply = self.rpc("reset", payload, timeout_ms=timeout_ms,
                         raw_buffers=prefix is not None)
        self.slot = int(reply["slot"])
        self.episode = reply.get("episode")
        if prefix is not None:
            reply["pred"] = np.asarray(reply["pred"])
            return reply
        return self.slot

    def step(self, obs, slot=None, timeout_ms=None):
        """One served ``step``: returns the reply dict (``pred`` is the
        model output row; stateful servers may add ``pos``, the
        position this observation consumed)."""
        use = self.slot if slot is None else slot
        if use is None:
            raise RuntimeError("step() before reset(): no episode slot")
        reply = self.rpc(
            "step",
            self._model_payload(
                {"slot": int(use), "episode": self.episode,
                 "obs": np.asarray(obs, np.float32)}
            ),
            timeout_ms=timeout_ms, raw_buffers=True,
        )
        reply["pred"] = np.asarray(reply["pred"])
        return reply

    def close_episode(self, timeout_ms=None):
        if self.slot is None:
            return False
        reply = self.rpc(
            "close",
            self._model_payload(
                {"slot": self.slot, "episode": self.episode}
            ),
            timeout_ms=timeout_ms,
        )
        self.slot = None
        self.episode = None
        return bool(reply.get("closed"))

    def stats(self, timeout_ms=None):
        return self.rpc("stats", timeout_ms=timeout_ms)

    def telemetry(self, timeout_ms=None):
        """The server process's telemetry snapshot (TelemetryHub merge
        shape: counters + serialized per-stage histograms)."""
        return self.rpc("telemetry", timeout_ms=timeout_ms)

    def register_with_hub(self, hub, name="serve"):
        """Wire the served process into a :class:`~blendjax.obs.hub.
        TelemetryHub` as a remote source (pulled per scrape over this
        RPC channel; a dead server surfaces as ``remote_errors``, never
        a failed scrape)."""
        hub.register_remote(name, lambda: self.telemetry(timeout_ms=500))
        return hub
