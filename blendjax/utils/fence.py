"""Trustworthy completion fences for timing and synchronization.

``jax.block_until_ready`` is only as honest as the backend's
implementation: on proxied/tunneled PJRT backends (observed on this
project's experimental ``axon`` TPU tunnel) it can acknowledge the
*local client buffer* rather than device completion — a 4096^3 bf16
matmul "blocks" in 0.04 ms (18x the chip's physical peak) and transfers
"complete" at 250x the wire's real bandwidth.  Any timing, duty-cycle,
or backpressure logic built on it silently measures fiction.

A VALUE FETCH cannot lie: the bytes of a computation's output cannot
reach the host before the computation (and every transfer it depends
on) actually finished.  This module provides:

- :func:`value_fence` — fence an arbitrary pytree by fetching one
  scalar reduced from every leaf (one tiny jit, cached per structure;
  one scalar D2H per call);
- :func:`fence_chain` — a running on-device accumulator for streaming
  loops: fold batches in as they are dispatched, fetch the accumulator
  at a measurement boundary to fence everything folded so far;
- :func:`fences_valid` — quick self-check of ``block_until_ready``
  against a known-FLOPs chained matmul (the full calibration lives in
  ``benchmarks/timing_calibration.py``).

The benchmark suite (``benchmarks/suite_device.py``) uses exactly this
methodology; see ``ROUND4_NOTES.md`` for the discovery write-up.
"""

from __future__ import annotations

import time

import numpy as np

# jax is imported lazily: this module rides in ``blendjax.utils``'s
# public surface, which jax-free fast-start processes (replay shards,
# the serve tier's LinearModel server) import for StageTimer — they
# must not pay (or hang on, with a dead TPU tunnel relay) ``import
# jax`` for fences they never call.
_jit = None


def _fns():
    global _jit
    if _jit is None:
        import jax
        import jax.numpy as jnp

        @jax.jit
        def leaf_sum(leaves):
            return sum(
                jnp.mean(leaf.astype(jnp.float32)) for leaf in leaves
            )

        @jax.jit
        def fold(acc, leaves):
            # one canonical reduction (jit inlines)
            return acc + leaf_sum(leaves)

        _jit = (leaf_sum, fold)
    return _jit


def _leaves(tree):
    import jax

    return [x for x in jax.tree.leaves(tree) if hasattr(x, "dtype")]


def value_fence(tree):
    """Block until every leaf of ``tree`` is actually materialized on
    device, by fetching a scalar that depends on all of them.  Returns
    the fetched float (occasionally useful as a checksum)."""
    leaves = _leaves(tree)
    if not leaves:
        return 0.0
    leaf_sum, _ = _fns()
    return float(np.asarray(leaf_sum(leaves)))


class fence_chain:
    """Streaming fence: ``fold`` each dispatched batch into an on-device
    scalar chain, ``sync`` at measurement boundaries.

    The fold is one fused reduction per batch (dispatched async, cheap);
    ``sync`` costs one scalar fetch and fences EVERY batch folded since
    construction — which is what a throughput window must bill::

        chain = fence_chain()
        t0 = time.perf_counter()
        for batch in stream:
            state, loss = train_step(state, batch)
            chain.fold(loss)
        chain.sync()                      # all steps actually retired
        elapsed = time.perf_counter() - t0
    """

    def __init__(self):
        import jax.numpy as jnp

        self._acc = jnp.float32(0.0)

    def fold(self, tree):
        leaves = _leaves(tree)
        if leaves:
            _, fold = _fns()
            self._acc = fold(self._acc, leaves)

    def sync(self):
        """Fetch the accumulator — returns only when everything folded
        has truly executed/landed."""
        return float(np.asarray(self._acc))


def fences_valid(peak_flops_per_sec, n=2048, reps=2, slack=1.02):
    """Is ``block_until_ready`` a real fence on this backend?

    Times one ``n^3`` bf16 matmul under ``block_until_ready``; if the
    implied FLOP/s beat ``peak_flops_per_sec`` the fence is phantom.
    Returns ``(block_ok, details)``.  Costs two small matmuls; use
    ``benchmarks/timing_calibration.py`` for the full chained-matmul
    calibration with value-fetch cross-checks.
    """
    import jax
    import jax.numpy as jnp

    x = jax.random.normal(jax.random.PRNGKey(0), (n, n), jnp.bfloat16)
    w = jax.random.normal(jax.random.PRNGKey(1), (n, n), jnp.bfloat16)
    mm = jax.jit(lambda a, b: a @ b)
    value_fence(mm(x, w))  # compile + land operands
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        jax.block_until_ready(mm(x, w))
        best = min(best, time.perf_counter() - t0)
    implied = 2.0 * n ** 3 / max(best, 1e-9)
    ok = implied <= peak_flops_per_sec * slack
    return ok, {"min_s": best, "implied_flops_per_sec": implied,
                "peak_flops_per_sec": peak_flops_per_sec}
