"""Cross-cutting utilities (stage timing / duty-cycle observability)."""

from blendjax.utils.timing import StageTimer

__all__ = ["StageTimer"]
