"""Cross-cutting utilities: stage timing / duty-cycle observability,
trustworthy completion fences, and train-state checkpointing."""

from blendjax.utils.checkpoint import (
    load_pytree,
    load_train_state,
    save_pytree,
    save_train_state,
)
from blendjax.utils.fence import fence_chain, fences_valid, value_fence
from blendjax.utils.timing import StageTimer

__all__ = [
    "StageTimer",
    "value_fence",
    "fence_chain",
    "fences_valid",
    "save_pytree",
    "load_pytree",
    "save_train_state",
    "load_train_state",
]
