"""Cross-cutting utilities: stage timing / duty-cycle observability and
train-state checkpointing."""

from blendjax.utils.checkpoint import (
    load_pytree,
    load_train_state,
    save_pytree,
    save_train_state,
)
from blendjax.utils.timing import StageTimer

__all__ = [
    "StageTimer",
    "save_pytree",
    "load_pytree",
    "save_train_state",
    "load_train_state",
]
