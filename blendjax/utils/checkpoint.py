"""Train-state checkpointing.

The reference has no model checkpointing (its checkpoint/resume analog is
stream record/replay, SURVEY.md §5 — blendjax keeps that in
``btt.FileRecorder``/``FileDataset``).  This module adds the model-state
half: save/restore arbitrary jax pytrees (params, optimizer state,
``TrainState``) to a single ``.npz``.

Leaves are stored by flattening order, which is deterministic for a fixed
pytree structure; ``load_pytree`` restores into a target pytree of the same
structure (shape/dtype checked).  No orbax dependency: nothing here is
sharding-aware — for multi-host sharded states, gather or use orbax; for
every blendjax workload (replicated or host-local states) this is enough
and has zero API churn.
"""

from __future__ import annotations

import logging
import os

import numpy as np

# jax is imported lazily inside the pytree helpers: the array-state half
# (save_state/load_state) is pure numpy, and its consumers now include
# jax-free processes (the replay shard service, which checkpoints its
# columns from a process that must start fast and never dial a device).

logger = logging.getLogger("blendjax")


def _replace_durable(tmp, path):
    """``os.replace`` with the durability the atomic-rename idiom alone
    does not buy: the tmp file's BYTES are fsynced before the rename
    (an unsynced rename can survive a host crash as a complete-looking
    zero-length/truncated file — the name committed, the data did not),
    and the parent directory entry is fsynced after it (best-effort:
    some filesystems refuse directory fsync)."""
    fd = os.open(tmp, os.O_RDWR)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)
    os.replace(tmp, path)
    try:
        dfd = os.open(os.path.dirname(os.path.abspath(path)),
                      os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(dfd)
    except OSError:
        pass  # the rename itself is durable-enough on refusal
    finally:
        os.close(dfd)


def save_pytree(path, tree):
    """Serialize a pytree of arrays to ``path`` (.npz; fsync + atomic
    rename, so a host crash leaves either the old file or the complete
    new one — never a truncated impostor)."""
    import jax

    leaves = jax.tree_util.tree_leaves(tree)
    arrays = {f"leaf_{i}": np.asarray(leaf) for i, leaf in enumerate(leaves)}
    tmp = f"{path}.tmp"
    with open(tmp, "wb") as f:
        np.savez(f, **arrays)
    _replace_durable(tmp, path)


def load_pytree(path, target):
    """Restore arrays into the structure of ``target``.

    ``target`` supplies the treedef (e.g. a freshly-initialized TrainState);
    leaf count, shapes, and dtypes must match the checkpoint.
    """
    import jax

    leaves, treedef = jax.tree_util.tree_flatten(target)
    with np.load(path) as data:
        if len(data.files) != len(leaves):
            raise ValueError(
                f"checkpoint has {len(data.files)} leaves, target expects "
                f"{len(leaves)}"
            )
        loaded = []
        for i, ref in enumerate(leaves):
            arr = data[f"leaf_{i}"]
            ref_arr = np.asarray(ref)
            if arr.shape != ref_arr.shape:
                raise ValueError(
                    f"leaf {i}: checkpoint shape {arr.shape} != target "
                    f"{ref_arr.shape}"
                )
            loaded.append(arr.astype(ref_arr.dtype))
    return jax.tree_util.tree_unflatten(treedef, loaded)


def save_state(path, arrays, meta=None):
    """Serialize named arrays + a JSON-able metadata dict to ``path``
    (.npz, atomic rename) — the sibling of :func:`save_pytree` for
    states that are NOT fixed-structure pytrees (e.g. a replay buffer's
    columns + ring indices + RNG state, whose keys vary per schema).

    ``meta`` may hold anything ``json.dumps`` accepts — Python ints of
    any size round-trip exactly, so numpy bit-generator states (128-bit
    ints) are safe.
    """
    import json

    if "__meta__" in arrays:
        raise ValueError("'__meta__' is reserved for the metadata channel")
    payload = {k: np.asarray(v) for k, v in arrays.items()}
    payload["__meta__"] = np.frombuffer(
        json.dumps(meta or {}).encode(), np.uint8
    )
    tmp = f"{path}.tmp"
    with open(tmp, "wb") as f:
        np.savez(f, **payload)
    _replace_durable(tmp, path)


def load_state(path):
    """Restore ``(arrays, meta)`` written by :func:`save_state`."""
    import json

    with np.load(path) as data:
        arrays = {k: data[k] for k in data.files if k != "__meta__"}
        meta = json.loads(bytes(data["__meta__"]).decode()) \
            if "__meta__" in data.files else {}
    return arrays, meta


def save_train_state(path, state):
    """Persist a :class:`blendjax.models.train.TrainState`."""
    save_pytree(path, state)


def load_train_state(path, template_state):
    """Restore a TrainState into ``template_state``'s structure."""
    return load_pytree(path, template_state)


class CheckpointManager:
    """Step-numbered checkpoints with retention and latest-step resume.

    Two backends:

    - ``'npz'`` (default): one atomic ``step_<N>.npz`` per step via
      :func:`save_pytree` — dependency-free, host-local arrays.
    - ``'orbax'``: ``orbax.checkpoint.PyTreeCheckpointer`` per step —
      sharding-aware (restores multi-host ``jax.Array`` states in place on
      TPU pods); requires the ``orbax-checkpoint`` package.

    Usage::

        mgr = CheckpointManager(dir, max_to_keep=3)
        mgr.save(step, state)
        state = mgr.restore(template_state)        # latest
        start = (mgr.latest_step() or -1) + 1      # resume loop
    """

    def __init__(self, directory, max_to_keep=3, backend="npz",
                 counters=None):
        if backend not in ("npz", "orbax"):
            raise ValueError(f"unknown backend {backend!r}")
        self.directory = os.path.abspath(directory)
        self.max_to_keep = max_to_keep
        self.backend = backend
        #: optional EventCounters sink (``ha_restore_fallbacks``); the
        #: instance attribute below reports fallbacks either way
        self.counters = counters
        #: restores that fell back past an unloadable latest checkpoint
        self.restore_fallbacks = 0
        os.makedirs(self.directory, exist_ok=True)
        if backend == "orbax":
            try:
                import orbax.checkpoint as ocp
            except ImportError as exc:
                # surfaced at CONSTRUCTION, not mid-save: an absent
                # optional dependency must fail before any training
                # step trusts this manager with its state
                raise ImportError(
                    "CheckpointManager(backend='orbax') requires the "
                    "optional 'orbax-checkpoint' package, which is not "
                    "installed; pip install orbax-checkpoint, or use "
                    "backend='npz' (the dependency-free default — "
                    "sufficient for replicated/host-local states)"
                ) from exc

            self._ckptr = ocp.PyTreeCheckpointer()

    # -- step bookkeeping ---------------------------------------------------

    def _path(self, step):
        name = f"step_{step:08d}"
        return os.path.join(
            self.directory, name + (".npz" if self.backend == "npz" else "")
        )

    def all_steps(self):
        steps = []
        for name in os.listdir(self.directory):
            if not name.startswith("step_"):
                continue
            path = os.path.join(self.directory, name)
            # Only count complete slots: an interrupted save leaves a
            # 'step_N.npz.tmp' behind (save_pytree writes tmp then renames)
            # which must not shadow a real step or poison latest_step().
            if self.backend == "npz":
                if not name.endswith(".npz") or not os.path.isfile(path):
                    continue
            elif not os.path.isdir(path):
                continue
            stem = name.split(".")[0]
            try:
                steps.append(int(stem[len("step_"):]))
            except ValueError:
                continue
        return sorted(set(steps))

    def latest_step(self):
        steps = self.all_steps()
        return steps[-1] if steps else None

    # -- save / restore -----------------------------------------------------

    def save(self, step, state):
        path = self._path(step)
        if self.backend == "npz":
            save_pytree(path, state)
        else:
            import jax

            self._ckptr.save(path, jax.tree.map(lambda x: x, state), force=True)
        self._retain()
        return path

    def restore(self, template, step=None):
        """Restore ``step`` (default: latest) into ``template``'s
        structure.  Raises FileNotFoundError when no checkpoint exists.

        With ``step=None`` an unloadable latest checkpoint (torn or
        truncated by a host crash that outran the fsync of an older
        writer, or deleted by a concurrent save's retention between the
        listing and the open) FALLS BACK to the previous step — counted
        in :attr:`restore_fallbacks` (and ``ha_restore_fallbacks`` when
        a counter sink is attached) and warned, never silent; the
        original error surfaces only when every step fails.  An
        EXPLICIT ``step`` keeps the strict contract: its failure
        raises."""
        if step is not None:
            return self._restore_step(template, step)
        first_exc = None
        for _attempt in range(8):
            steps = self.all_steps()
            if not steps and first_exc is None:
                raise FileNotFoundError(
                    f"no checkpoints under {self.directory}"
                )
            for i, s in enumerate(reversed(steps)):
                try:
                    restored = self._restore_step(template, s)
                except Exception as exc:  # noqa: BLE001 - fall back
                    if first_exc is None:
                        first_exc = exc
                    self.restore_fallbacks += 1
                    if self.counters is not None:
                        self.counters.incr("ha_restore_fallbacks")
                    logger.warning(
                        "checkpoint step %d under %s failed to load "
                        "(%s: %s); falling back to the previous step",
                        s, self.directory, type(exc).__name__, exc,
                    )
                    continue
                if i > 0 or _attempt > 0:
                    logger.warning(
                        "restored checkpoint step %d after newer "
                        "step(s) failed to load", s,
                    )
                return restored
            # every listed step failed: if the directory moved under
            # us (a concurrent save's retention unlinked the step we
            # just picked), re-list and retry instead of declaring the
            # whole directory dead on a stale snapshot
            if self.all_steps() == steps:
                break
        raise RuntimeError(
            f"every checkpoint under {self.directory} failed to load; "
            f"first error: {type(first_exc).__name__}: {first_exc}"
        ) from first_exc

    def _restore_step(self, template, step):
        path = self._path(step)
        if self.backend == "npz":
            return load_pytree(path, template)
        import jax

        restored = self._ckptr.restore(path, item=template)
        leaves, treedef = jax.tree_util.tree_flatten(template)
        new_leaves = jax.tree_util.tree_leaves(restored)
        return jax.tree_util.tree_unflatten(treedef, new_leaves)

    def _retain(self):
        if self.max_to_keep is None:
            return
        import shutil

        steps = self.all_steps()
        for step in steps[: max(0, len(steps) - self.max_to_keep)]:
            path = self._path(step)
            if os.path.isdir(path):
                shutil.rmtree(path, ignore_errors=True)
            else:
                try:
                    os.unlink(path)
                except OSError:
                    pass
