"""Train-state checkpointing.

The reference has no model checkpointing (its checkpoint/resume analog is
stream record/replay, SURVEY.md §5 — blendjax keeps that in
``btt.FileRecorder``/``FileDataset``).  This module adds the model-state
half: save/restore arbitrary jax pytrees (params, optimizer state,
``TrainState``) to a single ``.npz``.

Leaves are stored by flattening order, which is deterministic for a fixed
pytree structure; ``load_pytree`` restores into a target pytree of the same
structure (shape/dtype checked).  No orbax dependency: nothing here is
sharding-aware — for multi-host sharded states, gather or use orbax; for
every blendjax workload (replicated or host-local states) this is enough
and has zero API churn.
"""

from __future__ import annotations

import os

import jax
import numpy as np


def save_pytree(path, tree):
    """Serialize a pytree of arrays to ``path`` (.npz, atomic rename)."""
    leaves = jax.tree_util.tree_leaves(tree)
    arrays = {f"leaf_{i}": np.asarray(leaf) for i, leaf in enumerate(leaves)}
    tmp = f"{path}.tmp"
    with open(tmp, "wb") as f:
        np.savez(f, **arrays)
    os.replace(tmp, path)


def load_pytree(path, target):
    """Restore arrays into the structure of ``target``.

    ``target`` supplies the treedef (e.g. a freshly-initialized TrainState);
    leaf count, shapes, and dtypes must match the checkpoint.
    """
    leaves, treedef = jax.tree_util.tree_flatten(target)
    with np.load(path) as data:
        if len(data.files) != len(leaves):
            raise ValueError(
                f"checkpoint has {len(data.files)} leaves, target expects "
                f"{len(leaves)}"
            )
        loaded = []
        for i, ref in enumerate(leaves):
            arr = data[f"leaf_{i}"]
            ref_arr = np.asarray(ref)
            if arr.shape != ref_arr.shape:
                raise ValueError(
                    f"leaf {i}: checkpoint shape {arr.shape} != target "
                    f"{ref_arr.shape}"
                )
            loaded.append(arr.astype(ref_arr.dtype))
    return jax.tree_util.tree_unflatten(treedef, loaded)


def save_train_state(path, state):
    """Persist a :class:`blendjax.models.train.TrainState`."""
    save_pytree(path, state)


def load_train_state(path, template_state):
    """Restore a TrainState into ``template_state``'s structure."""
    return load_pytree(path, template_state)
