"""Lightweight per-stage timing — the observability the reference lacks
(SURVEY.md §5: "The TPU build should add lightweight stage timestamps
(render / serialize / recv / device_put) since the north-star metric is TPU
duty-cycle").

Usage::

    timer = StageTimer()
    with timer.stage("recv"):
        msg = sock.recv()
    ...
    timer.summary()   # {'recv': {'count': n, 'total_s': t, 'mean_ms': m,
                      #           'p50_ms': ..., 'p90_ms': ..., 'p99_ms': ...,
                      #           'max_ms': ...}, ...}
    timer.duty_cycle("step")   # fraction of wall time inside 'step'

Every ``add`` also lands in a fixed-memory log-bucketed latency
histogram (:class:`blendjax.obs.histogram.LatencyHistogram`), so the
summary carries per-stage p50/p90/p99/max — the percentile surface the
telemetry plane (docs/observability.md) scrapes and merges across
processes.  ``histograms=False`` opts out.

Pass ``trace=True`` to additionally record one event per stage interval
and ``export_chrome_trace(path)`` them as Chrome trace-event JSON —
loadable in ``chrome://tracing`` / Perfetto, with loader workers, the
prefetch thread and the train loop on separate rows so feed stalls are
visible as gaps.  Tracing is off by default (zero per-stage overhead
beyond the two timestamps), and the event ring is bounded
(``trace_cap``; evictions counted in ``trace_dropped``) so multi-hour
traced runs cannot exhaust host memory.
"""

from __future__ import annotations

import json
import math
import os
import threading
import time
from collections import defaultdict, deque
from contextlib import contextmanager

from blendjax.obs import histogram as _histogram
from blendjax.obs.histogram import LatencyHistogram

# hot-path constants for the inlined histogram update in StageTimer.add
_hist_frexp = math.frexp
_HIST_TOP = _histogram.NBUCKETS - 1
_HIST_SUBBITS = _histogram.SUBBITS

#: Canonical feed-pipeline stage names (see docs/feed_pipeline.md).
#: ``recv``/``collate``/``device_put`` cover the legacy path; the
#: arena-pooled assembly adds ``arena_wait`` (blocked acquiring a free
#: batch arena — i.e. trainer backpressure), ``scatter`` (wire frame ->
#: batch-buffer copy) and ``recycle`` (arena returned after the device
#: transfer completes).  StageTimer itself accepts any name; this tuple
#: is the shared vocabulary bench.py and the suite report under.
FEED_STAGES = (
    "recv", "collate", "arena_wait", "scatter", "recycle", "device_put",
)

#: Canonical fault/health event names (see docs/fault_tolerance.md).
#: ``EventCounters`` accepts any name; this tuple is the shared vocabulary
#: the fault layer increments and ``FleetSupervisor.health()`` reports —
#: every name is present (zero) in a health snapshot even before its first
#: event, so dashboards and tests need no existence checks.
FLEET_EVENTS = (
    "deaths", "restarts", "retries", "timeouts", "failures", "quarantines",
    "readmissions", "circuit_opens", "circuit_rejections",
    "stream_timeouts", "stream_ring_vanished", "transfer_gate_backstops",
    # async env pipeline (EnvPool.step_async/step_wait):
    # ``ready_waits`` — step_wait calls that actually blocked for a reply;
    # ``stale_replies`` — replies with no matching in-flight request
    # (duplicate delivery, or orphaned by a quarantine drain);
    # ``inflight_discards`` — in-flight requests consumed without
    # surfacing a real transition (quarantine drain, post-``done`` frames,
    # pipeline flush); a reply lost ahead of an out-of-order match is NOT
    # discarded — it is re-sent and answered from the producer reply cache
    "ready_waits", "stale_replies", "inflight_discards",
    # record path: ``record_drops`` — messages FileRecorder refused because
    # its fixed capacity was reached (the recording is truncated, not the
    # stream; see btt/file.py)
    "record_drops",
    # watchdog respawn pacing: ``watchdog_backoff_jitter_ms`` — total
    # milliseconds of per-member randomized delay FleetWatchdog inserted
    # before respawns, so N members killed together do not relaunch in
    # lockstep and stampede the gateway's re-admission scrape (see
    # docs/fault_tolerance.md; the jitter itself is `respawn_jitter_s`)
    "watchdog_backoff_jitter_ms",
)

#: Canonical experience-replay event names (see docs/replay.md).  Same
#: contract as ``FLEET_EVENTS``: any ``EventCounters`` instance accepts
#: them, and ``FleetSupervisor.health()`` zero-fills every name so
#: dashboards need no existence checks.
#: ``replay_appends`` — transitions accepted into the ring;
#: ``replay_overwrites`` — appends that evicted a live transition (ring
#: wraparound: the buffer is at capacity and recycling oldest-first);
#: ``replay_excluded`` — appends flagged unhealthy (synthetic
#: degraded-mode transitions: stored for inspection, never sampled);
#: ``replay_samples`` — batches drawn;
#: ``replay_sample_waits`` — sample calls that blocked on an
#: underfilled buffer (learner outpacing the actor);
#: ``replay_priority_updates`` — update_priorities calls applied.
#: ``replay_sample_skips`` — off-policy learner tail draws skipped
#: because the buffer (or its live shards) could not serve the batch;
#: sharded replay service (docs/replay.md "Sharded replay service"):
#: ``replay_shard_quarantined`` — a shard stopped answering RPCs (or its
#: process died) and was isolated; sampling renormalizes strata over the
#: live shards and continues degraded;
#: ``replay_shard_readmissions`` — a shard passed the re-admission
#: handshake (restored checkpoint + ``.btr`` tail verified, journal
#: flushed) and rejoined the draw domain;
#: ``replay_shard_journal`` — appends owned by a quarantined shard held
#: client-side (flushed on re-admission, never lost);
#: ``replay_shard_lost`` — rows a restarted shard could not account for
#: (it restored an older state than the client acked); their slots are
#: invalidated instead of serving wrong rows;
#: per-request wire-bytes accounting (docs/transport.md): a shard
#: counts every RPC payload byte it moves, split by wire —
#: ``replay_wire_bytes`` over the ZMQ socket, ``replay_shm_bytes``
#: through the ShmRPC rings — so the shm-vs-tcp byte saving is
#: observable in a telemetry scrape, not just inferred from latency.
REPLAY_EVENTS = (
    "replay_appends", "replay_overwrites", "replay_excluded",
    "replay_samples", "replay_sample_waits", "replay_priority_updates",
    "replay_sample_skips",
    "replay_shard_quarantined", "replay_shard_readmissions",
    "replay_shard_journal", "replay_shard_lost",
    "replay_wire_bytes", "replay_shm_bytes",
)

#: Canonical policy-serving event names (see docs/serving.md).  Same
#: contract as ``FLEET_EVENTS``: any ``EventCounters`` accepts them and
#: the TelemetryHub zero-fills every name in every scrape.
#: ``serve_requests`` — requests admitted (any command);
#: ``serve_replies`` — replies sent (errors included);
#: ``serve_batches`` — batched compute ticks executed;
#: ``serve_batch_pad`` — padding rows added to reach a bucket size
#: (wasted compute rows, the bucket/recompile tradeoff's price);
#: ``serve_cache_hits`` — retried requests answered from the reply
#: cache (exactly-once: no second decode for the same correlation id);
#: ``serve_dup_inflight`` — duplicates of a still-queued request
#: dropped at admission (the original's reply answers both);
#: ``serve_resets`` — episodes admitted (slot allocations);
#: ``serve_closes`` — episodes closed by their client;
#: ``serve_evictions`` — idle slots reclaimed by the allocator;
#: ``serve_slot_denied`` — resets refused because no slot was free;
#: ``serve_errors`` — requests that errored: answered with an error
#: reply, or (batched mode only) dropped because their frames were
#: undecodable — the one case with no reply, healed by the client's
#: retry;
#: ``serve_prefills`` — episodes admitted WITH a T-step observation
#: prefix replayed in one teacher-forced batched pass (docs/serving.md
#: "Batched prefill admission") instead of T serial decode steps;
#: per-request wire-bytes accounting (docs/transport.md): the server
#: counts every request/reply payload byte it moves, split by wire —
#: ``serve_wire_bytes`` over the ZMQ socket, ``serve_shm_bytes``
#: through the ShmRPC rings.
SERVE_EVENTS = (
    "serve_requests", "serve_replies", "serve_batches",
    "serve_batch_pad", "serve_cache_hits", "serve_dup_inflight",
    "serve_resets", "serve_closes", "serve_evictions",
    "serve_slot_denied", "serve_errors", "serve_prefills",
    "serve_wire_bytes", "serve_shm_bytes",
)

#: Canonical serve-gateway event names (see docs/serving.md
#: "ServeGateway").  Same contract as ``FLEET_EVENTS``: any
#: ``EventCounters`` accepts them and the TelemetryHub zero-fills every
#: name in every scrape.  The gateway's per-request counters carry the
#: ``gateway_`` prefix INSTEAD of reusing the ``serve_*`` vocabulary,
#: so a hub that registers the gateway AND its replicas (the documented
#: setup) folds distinct names — one client request must not read as
#: two ``serve_requests`` in the merged scrape.
#: ``gateway_requests`` — client requests admitted at the front (any
#: command);
#: ``gateway_replies`` — replies sent to clients (forwarded replica
#: replies AND gateway-local answers, errors included);
#: ``gateway_errors`` — requests the gateway errored or dropped
#: (unknown command, no healthy replica, undecodable frames);
#: ``gateway_cache_hits`` — retries answered from the gateway's
#: mutating-reply cache (exactly-once: the fleet never sees them);
#: ``gateway_dup_inflight`` — retries of a still-in-flight forward
#: re-sent to the SAME replica (whose dedupe keeps them exactly-once);
#: ``gateway_routed`` — requests forwarded to a replica (any command);
#: ``gateway_affinity_hits`` — step/close requests routed by a live
#: episode lease to the replica that owns its KV-cache row;
#: ``gateway_rebalances`` — fresh-episode routes where the load ranking
#: (queue depth + SERVE_STAGES p99 from the cached telemetry scrape)
#: overrode plain rotation;
#: ``gateway_replica_quarantined`` — a replica stopped answering (scrape
#: timeout, or the watchdog reported its death) and was isolated: its
#: leases are invalidated and fresh episodes avoid it;
#: ``gateway_replica_respawns`` — a quarantined replica answered a
#: scrape again (watchdog respawn landed) and rejoined the route set;
#: ``gateway_stale_lease_redirects`` — step/close requests whose lease
#: pointed at a dead/forgotten episode, answered with the actionable
#: stale-lease error (the client ``reset()``s onto a healthy replica);
#: ``gateway_drains`` — replicas put into drain (no fresh episodes,
#: live ones finish).
#: The sharded data plane (front/worker/control split, docs/serving.md)
#: adds:
#: ``gateway_worker_deaths`` — gateway worker processes the watchdog
#: reported dead (SIGKILL, crash);
#: ``gateway_worker_respawns`` — worker processes relaunched by the
#: watchdog and re-admitted by the control plane;
#: ``gateway_lease_rehash`` — lease-owned requests the front answered
#: for a dead worker with the actionable stale-lease error (the
#: client's ``reset()`` re-hashes onto a live worker);
#: ``gateway_snapshot_applies`` — versioned control-state snapshots a
#: worker adopted (replica health/drain/canary verdicts published by
#: the control plane; stale versions are ignored, not counted);
#: ``gateway_snapshot_publishes`` — snapshot versions the control plane
#: published to its workers (one count per version, not per worker);
#: ``gateway_front_relays`` — client requests the front relayed to a
#: worker on its behalf (rendezvous, proxied clients); direct-dialed
#: steady-state traffic never lands here.
GATEWAY_EVENTS = (
    "gateway_requests", "gateway_replies", "gateway_errors",
    "gateway_cache_hits", "gateway_dup_inflight",
    "gateway_routed", "gateway_affinity_hits", "gateway_rebalances",
    "gateway_replica_quarantined", "gateway_replica_respawns",
    "gateway_stale_lease_redirects", "gateway_drains",
    "gateway_worker_deaths", "gateway_worker_respawns",
    "gateway_lease_rehash", "gateway_snapshot_applies",
    "gateway_snapshot_publishes", "gateway_front_relays",
)

#: Canonical weight-bus event names (see docs/weight_bus.md).  Same
#: contract as ``FLEET_EVENTS``: any ``EventCounters`` accepts them and
#: the TelemetryHub zero-fills every name in every scrape.
#: ``weight_published`` — versioned snapshots streamed by a publisher
#: (rollback republishes included);
#: ``weight_publish_bytes`` — snapshot payload bytes streamed (summed
#: over subscribers; deltas ship only changed leaves);
#: ``weight_syncs`` — full-snapshot catch-ups served to late joiners /
#: re-syncing subscribers;
#: ``weight_adopted`` — complete, digest-verified snapshots hot-swapped
#: into a serving model between ticks;
#: ``weight_torn_discarded`` — partial snapshot streams discarded
#: (publisher died mid-stream, a superseding begin, a sequence gap, an
#: undecodable frame) — the server keeps serving the last good version;
#: ``weight_digest_rejected`` — completed streams rejected on checksum
#: mismatch (whole-stream or per-leaf), never half-applied;
#: ``weight_apply_failed`` — verified snapshots the model refused
#: (structure/shape mismatch); the last good version keeps serving;
#: ``weight_canary_starts`` — canary windows opened on a gateway;
#: ``weight_canary_routes`` — fresh episodes deliberately routed to the
#: canary version's replicas;
#: ``weight_canary_promotions`` — canary versions promoted to stable;
#: ``weight_canary_rollbacks`` — canary versions rolled back (fresh
#: traffic stops routing to them);
#: ``weight_rollback_publishes`` — rollback republishes: a prior
#: version's weights re-published under a fresh higher version id.
WEIGHT_EVENTS = (
    "weight_published", "weight_publish_bytes", "weight_syncs",
    "weight_adopted", "weight_torn_discarded", "weight_digest_rejected",
    "weight_apply_failed",
    "weight_canary_starts", "weight_canary_routes",
    "weight_canary_promotions", "weight_canary_rollbacks",
    "weight_rollback_publishes",
)

#: Canonical scenario-plane event names (see docs/scenarios.md).  Same
#: contract as ``FLEET_EVENTS``: any ``EventCounters`` accepts them and
#: the TelemetryHub zero-fills every name in every scrape.
#: ``scenario_samples`` — concrete parameter dicts sampled from a
#: :class:`~blendjax.scenario.ScenarioSpec` (seeded draws over its
#: randomization ranges);
#: ``scenario_pushes`` — parameter pushes sent into running producers
#: over the duplex control plane (the densityopt pattern, live
#: domain randomization);
#: ``scenario_push_failures`` — pushes that could not be delivered
#: (send timeout into a dead/stalled producer; the bounded-timeout
#: send is what keeps a SIGKILLed producer from wedging the
#: randomizer — the failed push is counted, never blocked on);
#: ``scenario_applies`` — pushed scenarios CONFIRMED applied: the
#: first transition stamped with the newly-pushed scenario id
#: observed back on the data plane (push is fire-and-forget; this is
#: the round-trip acknowledgement);
#: ``scenario_reassignments`` — scenarios re-pushed to a respawned /
#: re-admitted env over a fresh control channel (a quarantined env's
#: scenario must survive its producer's death);
#: ``scenario_curriculum_updates`` — curriculum reweight passes
#: executed (interval-gated);
#: ``scenario_mix_changes`` — reweight passes that actually CHANGED
#: the fleet's scenario mix (what a curriculum-shift test pins);
#: ``scenario_rows_stamped`` — replay rows appended carrying a
#: scenario id (the ``healthy``-key in-band pattern extended to
#: ``scenario``);
#: ``scenario_strata_draws`` — sampled batches drawn under a
#: NON-uniform scenario mix (per-scenario strata shaping the draw; a
#: uniform mix never counts here — it is byte-identical to the
#: scenario-less draw stream by contract);
#: ``scenario_serve_requests`` — scenario-labelled serve replies
#: recorded by a :class:`~blendjax.serve.gateway.ServeGateway` into
#: its per-scenario request/latency records.
SCENARIO_EVENTS = (
    "scenario_samples", "scenario_pushes", "scenario_push_failures",
    "scenario_applies", "scenario_reassignments",
    "scenario_curriculum_updates", "scenario_mix_changes",
    "scenario_rows_stamped", "scenario_strata_draws",
    "scenario_serve_requests",
)

#: Canonical learner-failover (HA) event names (see
#: docs/fault_tolerance.md "Learner failover").  Same contract as
#: ``FLEET_EVENTS``: any ``EventCounters`` accepts them and the
#: TelemetryHub zero-fills every name in every scrape.
#: ``ha_ckpt_saves`` — coordinated train-state checkpoints committed
#: (manifest written: TrainState + counters + curriculum + replay cut
#: + bus version form one consistent cut);
#: ``ha_ckpt_bytes`` — bytes serialized into committed checkpoints;
#: ``ha_ckpt_skipped`` — due checkpoints skipped because the previous
#: background serialization was still in flight (the bounded-stall
#: contract: the update loop never queues up checkpoint work);
#: ``ha_ckpt_failures`` — checkpoint attempts that failed (counted and
#: logged; never raised into the update loop);
#: ``ha_ckpt_evicted`` — old checkpoints removed by retention;
#: ``ha_restores`` — successful restores from a manifest;
#: ``ha_restore_fallbacks`` — restores that fell back to an OLDER
#: step/manifest because the latest failed to load (torn/truncated
#: file after a host crash) — counted and warned, never silent;
#: ``ha_learner_deaths`` — supervised learner-process deaths;
#: ``ha_learner_respawns`` — successful supervised learner respawns;
#: ``ha_resume_publishes`` — checkpointed params republished on the
#: weight bus at resume under a fresh higher version id (the serve
#: tier rolls forward across the respawn).
HA_EVENTS = (
    "ha_ckpt_saves", "ha_ckpt_bytes", "ha_ckpt_skipped",
    "ha_ckpt_failures", "ha_ckpt_evicted",
    "ha_restores", "ha_restore_fallbacks",
    "ha_learner_deaths", "ha_learner_respawns", "ha_resume_publishes",
)

#: Canonical autoscale control-plane event names (see
#: docs/autoscaling.md).  Same contract as ``FLEET_EVENTS``: any
#: ``EventCounters`` accepts them and the TelemetryHub zero-fills every
#: name in every scrape.
#: ``autoscale_ticks`` — controller decision passes executed;
#: ``autoscale_holds`` — decision passes that wanted to act but were
#: suppressed by a per-direction cooldown, the hysteresis band, the
#: min/max fleet bounds, or a transition already in flight (the
#: single-transition-at-a-time rule);
#: ``autoscale_scale_ups`` — serve scale-ups COMMITTED: a new replica
#: spawned, admitted at the gateway, and survived its post-action
#: healthy window;
#: ``autoscale_scale_downs`` — serve scale-downs committed: a replica
#: drained to zero leases, the shrunk fleet survived the healthy
#: window, and the process was retired and its ``/dev/shm`` swept;
#: ``autoscale_rollbacks`` — transitions ROLLED BACK by the verifier
#: (error-rate or p99 regression in the healthy window): the draining
#: replica was re-admitted, or the freshly-added replica was drained
#: back out — capacity returns to the pre-decision state;
#: ``autoscale_drain_timeouts`` — scale-downs abandoned because live
#: leases did not finish or idle out inside the bounded drain grace
#: window (the victim is undrained; counted under rollbacks too);
#: ``autoscale_replica_spawns`` — replica processes spawned by the
#: controller (before verification — a rolled-back spawn still counts);
#: ``autoscale_replicas_retired`` — replica processes retired (drained,
#: verified, terminated, shm swept);
#: ``autoscale_adoptions`` — in-flight transitions a (re)started
#: controller ADOPTED from observed fleet state instead of acting anew
#: (a replica already draining, an un-verified extra replica): the
#: idempotence witness for the SIGKILL-the-controller drill;
#: ``autoscale_reshard_handoffs`` — replay shard handoffs COMMITTED
#: (source checkpoint restored by the new shard, ``written_since``
#: reconciled, client slot-range map cut over);
#: ``autoscale_reshard_aborts`` — handoffs aborted whole (new shard
#: died / checkpoint or seq mismatch / reconcile overflow): the client
#: map is untouched and the source shard keeps serving its range;
#: ``autoscale_reshard_rows_copied`` — rows copied source→new shard
#: during handoffs (checkpoint restore is not counted; this is the
#: ``written_since`` reconcile traffic).
AUTOSCALE_EVENTS = (
    "autoscale_ticks", "autoscale_holds",
    "autoscale_scale_ups", "autoscale_scale_downs",
    "autoscale_rollbacks", "autoscale_drain_timeouts",
    "autoscale_replica_spawns", "autoscale_replicas_retired",
    "autoscale_adoptions",
    "autoscale_reshard_handoffs", "autoscale_reshard_aborts",
    "autoscale_reshard_rows_copied",
)

#: Canonical autoscale stage names (see docs/autoscaling.md):
#: ``autoscale_tick`` (one decision pass: scrape-derived load fold +
#: rule evaluation), ``autoscale_resize`` (decision → fleet healthy at
#: the new size, the whole transition including drain/verify — the
#: ``resize_settle_s`` bench metric is this stage's observation),
#: ``autoscale_drain`` (drain issued → victim's live leases at zero),
#: ``autoscale_handoff`` (shard handoff: source checkpoint → client
#: map cutover).
AUTOSCALE_STAGES = (
    "autoscale_tick", "autoscale_resize", "autoscale_drain",
    "autoscale_handoff",
)

#: Canonical learner-failover stage names (see docs/fault_tolerance.md
#: "Learner failover"): ``ha_snapshot`` (the synchronous barrier on the
#: update loop — host-gather of the TrainState plus the coordinated
#: replay cut; the only stall the checkpointer charges training),
#: ``ha_serialize`` (background thread: npz writes + fsync + manifest
#: commit + retention), ``ha_restore`` (manifest load + train-state /
#: replay / curriculum restore at learner startup).
HA_STAGES = (
    "ha_snapshot", "ha_serialize", "ha_restore",
)

#: Canonical scenario-plane stage names (see docs/scenarios.md):
#: ``scenario_sample`` (one seeded spec sample — param-dict build),
#: ``scenario_push`` (one duplex send of a sampled param push into a
#: producer, bounded by the push timeout), ``scenario_reweight`` (one
#: curriculum reweight pass: strata scrape fold + mix decision).
SCENARIO_STAGES = (
    "scenario_sample", "scenario_push", "scenario_reweight",
)

#: Canonical weight-bus stage names (see docs/weight_bus.md):
#: ``weight_publish`` (snapshot + digest + chunk + stream, publisher
#: side), ``weight_assemble`` (chunk ingest + digest verification per
#: completed snapshot, subscriber side — compute only, not wall wait),
#: ``weight_swap`` (the between-ticks hot-swap: pytree rebuild +
#: ``model.apply_weights``).
WEIGHT_STAGES = (
    "weight_publish", "weight_assemble", "weight_swap",
)

#: Canonical serve-gateway stage names (see docs/serving.md), the
#: :class:`StageTimer` vocabulary :class:`~blendjax.serve.gateway.
#: ServeGateway` reports under: ``gw_route`` (request decode + routing
#: decision), ``gw_forward`` (re-encode + send to the chosen replica),
#: ``gw_reply`` (replica reply receive + forward back to the client).
#: Prefixed ``gw_`` so the hub's union stage namespace cannot alias the
#: server-side ``reply`` stage.
GATEWAY_STAGES = (
    "gw_route", "gw_forward", "gw_reply",
)

#: Canonical policy-serving stage names (see docs/serving.md), the
#: :class:`StageTimer` vocabulary the serve benchmark and
#: ``PolicyServer`` report under: ``queue_wait`` (request admission to
#: batch dequeue — the continuous-batching latency price), and the tick
#: processing: ``batch_assemble`` (drain + pad-to-bucket + host-side
#: array build), ``compute`` (the jitted model call, fenced),
#: ``reply`` (per-client scatter of the batch's replies).
SERVE_STAGES = (
    "queue_wait", "batch_assemble", "compute", "reply",
)

#: Canonical replay-path stage names (see docs/replay.md), the
#: :class:`StageTimer` vocabulary the replay benchmark and
#: ``ReplayBuffer`` report under: ``replay_append`` (row scatter into the
#: ring columns), ``sample_wait`` (blocked on an underfilled buffer),
#: ``sample_gather`` (index draw + columnar gather into the batch),
#: ``priority_update`` (sum-tree refresh after a learner step).
#: The sharded service adds ``shard_append`` (one append RPC to a shard,
#: wire + remote write + spill flush) and ``shard_gather`` (one gather
#: RPC: wire + remote columnar read + client-side scatter).
REPLAY_STAGES = (
    "replay_append", "sample_wait", "sample_gather", "priority_update",
    "shard_append", "shard_gather",
)

#: Canonical MPMD-pipeline event names (see docs/pipeline.md).  Same
#: contract as ``FLEET_EVENTS``: any ``EventCounters`` accepts them and
#: the TelemetryHub zero-fills every name in every scrape.  The driver
#: and each stage process count into their own sinks; the hub merge is
#: the fleet view.
#: ``pipe_updates`` — pipeline updates committed (stage side: SGD
#: applied at the update boundary; driver side: full
#: begin→feed→finish→commit rounds completed);
#: ``pipe_microbatches`` — microbatch records processed (stage side:
#: backward passes completed; driver side: microbatches fed);
#: ``pipe_feed_parks`` — feed stalls: the bounded in-flight window was
#: full, so the driver parked instead of allocating — the bubble
#: schedule acting as backpressure on the arena feed;
#: ``pipe_resends`` — in-flight activation/grad/target records re-sent
#: under the SAME correlation id after a missed ack (peer death or shm
#: demotion; the receiver's reply cache + ``(update, mb)`` dedup make
#: the resend exactly-once);
#: ``pipe_dup_records`` — duplicate records absorbed by that dedup (a
#: resent record whose original did land);
#: ``pipe_restarts`` — update attempts the driver abandoned and
#: replayed after reconciling a changed fleet (a stage died
#: mid-update);
#: ``pipe_rollbacks`` — stage-side param rollbacks to an earlier
#: committed boundary (checkpoint restore or rebuild-from-seed);
#: ``pipe_driver_rollbacks`` — rollback commands the driver issued
#: while reconciling stages to the lowest common applied update;
#: ``pipe_stage_respawns`` — stage incarnation changes the driver
#: observed at hello (the watchdog respawned a killed stage);
#: ``pipe_ckpt_restores`` — stage param restores from the per-stage
#: checkpoint cut (at process start or rollback);
#: ``pipe_wire_bytes`` — payload bytes through a stage server's wire
#: paths (both transports, both directions it counts).
PIPE_EVENTS = (
    "pipe_updates", "pipe_microbatches", "pipe_feed_parks",
    "pipe_resends", "pipe_dup_records",
    "pipe_restarts", "pipe_rollbacks", "pipe_driver_rollbacks",
    "pipe_stage_respawns", "pipe_ckpt_restores", "pipe_wire_bytes",
)

#: Canonical MPMD-pipeline stage names (see docs/pipeline.md), the
#: :class:`StageTimer` vocabulary the stage processes and the pipeline
#: driver report under: ``pipe_fwd`` (one microbatch forward through a
#: stage's owned layers), ``pipe_bwd`` (one microbatch backward — on
#: the last stage this is the fused forward+loss+backward unit),
#: ``pipe_apply`` (the SGD apply at an update commit), ``pipe_feed``
#: (driver: pushing one microbatch pair into the pipeline, parks
#: included), ``pipe_finish`` (driver: the grads-ready poll barrier
#: after the last microbatch — the visible tail of the 1F1B bubble).
PIPE_STAGES = (
    "pipe_fwd", "pipe_bwd", "pipe_apply", "pipe_feed", "pipe_finish",
)


class EventCounters:
    """Thread-safe named event counters — the numeric half of fleet
    observability (stage *times* live in :class:`StageTimer`; discrete
    *events* — retries, deaths, quarantines — live here).

    A process-wide default instance (:data:`fleet_counters`) is shared by
    the fault layer so counters aggregate across components without
    plumbing; pass a fresh instance for isolated accounting (tests,
    per-fleet supervisors).
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._counts = defaultdict(int)

    def incr(self, name, n=1):
        with self._lock:
            self._counts[name] += n

    def get(self, name):
        with self._lock:
            return self._counts.get(name, 0)

    def snapshot(self):
        """Copy of all counters as a plain dict."""
        with self._lock:
            return dict(self._counts)

    def reset(self):
        with self._lock:
            self._counts.clear()


#: Process-wide default counter registry (fault layer, TransferGate
#: backstop, stream timeouts).  Component constructors take a
#: ``counters=`` override for isolated accounting.
fleet_counters = EventCounters()


#: Default bound on the ``trace=True`` event ring: ~64k intervals is
#: hours of feed-stage tracing at typical batch rates while holding a
#: few MB at most.  Beyond it the OLDEST events are dropped (and counted
#: in :attr:`StageTimer.trace_dropped`) — the recent window is what a
#: stall investigation wants, and an unbounded list once exhausted host
#: memory on multi-hour traced runs.
DEFAULT_TRACE_CAP = 65536


class StageTimer:
    """Accumulates wall-clock time per named stage (thread-safe: stages are
    recorded from loader workers and the prefetch thread concurrently).

    With ``histograms=True`` (the default) every :meth:`add` also lands
    in a fixed-memory log-bucketed
    :class:`~blendjax.obs.histogram.LatencyHistogram`, so
    :meth:`summary` reports p50/p90/p99/max per stage alongside the
    means — the percentile surface ``health()``, the TelemetryHub and
    the bench artifacts read.  ``histograms=False`` opts out (the knob
    the ``telemetry_overhead_x`` bench compares against).
    """

    def __init__(self, trace=False, histograms=True,
                 trace_cap=DEFAULT_TRACE_CAP):
        self._lock = threading.Lock()
        self._trace = bool(trace)
        self._histograms = bool(histograms)
        self._trace_cap = int(trace_cap)
        self.reset()

    def reset(self):
        with self._lock:
            self._total = defaultdict(float)
            self._count = defaultdict(int)
            self._hist = {}
            self._events = deque(maxlen=self._trace_cap)
            self._trace_dropped = 0
            self._start = time.perf_counter()

    @contextmanager
    def stage(self, name):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.add(name, time.perf_counter() - t0, _t0=t0)

    def add(self, name, seconds, _t0=None, _frexp=_hist_frexp,
            _top=_HIST_TOP, _sub=_HIST_SUBBITS):
        with self._lock:
            self._total[name] += seconds
            self._count[name] += 1
            if self._histograms:
                h = self._hist.get(name)
                if h is None:
                    h = self._hist[name] = LatencyHistogram()
                # LatencyHistogram.add inlined AND thinned: this is the
                # feed/RL hot path, priced by telemetry_overhead_x
                # (floor 0.95).  The histogram's n/sum_s are NOT
                # maintained here — inside a StageTimer they duplicate
                # _count/_total exactly, so _sync_hist_locked derives
                # them at read time instead of paying two more
                # attribute RMWs per event
                us = seconds * 1e6
                if us < 1.0:
                    idx = 0
                else:
                    m, e = _frexp(us)
                    idx = ((e - 1) << _sub) + int((m + m - 1.0) *
                                                  (1 << _sub)) + 1
                    if idx > _top:
                        idx = _top
                h.counts[idx] += 1
                if seconds > h.max_s:
                    h.max_s = seconds
            if self._trace:
                start = _t0 if _t0 is not None else time.perf_counter() - seconds
                if len(self._events) == self._trace_cap:
                    self._trace_dropped += 1
                self._events.append(
                    (name, start, seconds, threading.get_ident())
                )

    def add_bulk(self, name, total_seconds, count):
        """Accumulate ``count`` pre-aggregated intervals in one locked
        update — for hot loops (e.g. the arena feed path at ~100 us per
        batch) where a per-interval :meth:`add` would itself be a
        measurable stage.  Not recorded as trace events (aggregates have
        no start times), and histogram entries land at the aggregate's
        MEAN (per-interval spread is already lost) — percentiles for a
        stage fed only through here degenerate to that mean."""
        if count <= 0:
            return
        with self._lock:
            self._total[name] += total_seconds
            self._count[name] += count
            if self._histograms:
                h = self._hist.get(name)
                if h is None:
                    h = self._hist[name] = LatencyHistogram()
                h.add_many(total_seconds / count, count)

    @property
    def wall_s(self):
        return time.perf_counter() - self._start

    def total_s(self, name):
        with self._lock:
            return self._total.get(name, 0.0)

    def count(self, name):
        with self._lock:
            return self._count.get(name, 0)

    def mean_ms(self, name):
        with self._lock:
            c = self._count.get(name, 0)
            return (self._total[name] / c) * 1e3 if c else 0.0

    def duty_cycle(self, name):
        """Fraction of wall time since reset spent inside ``name``."""
        wall = self.wall_s
        with self._lock:
            return self._total.get(name, 0.0) / wall if wall > 0 else 0.0

    @property
    def trace_dropped(self):
        """Trace events evicted from the bounded ring (oldest first)."""
        with self._lock:
            return self._trace_dropped

    def _sync_hist_locked(self, name):
        """The stage's histogram with ``n``/``sum_s`` derived from
        ``_count``/``_total`` (the hot-path :meth:`add` skips those two
        RMWs — inside a StageTimer they are exact duplicates)."""
        h = self._hist.get(name)
        if h is not None:
            h.n = self._count[name]
            h.sum_s = self._total[name]
        return h

    def percentiles(self, name):
        """``{"p50_ms","p90_ms","p99_ms","max_ms"}`` for a stage (zeros
        when unrecorded or histograms are off)."""
        with self._lock:
            h = self._sync_hist_locked(name)
            if h is None:
                return {"p50_ms": 0.0, "p90_ms": 0.0, "p99_ms": 0.0,
                        "max_ms": 0.0}
            return h.percentiles()

    def summary(self):
        with self._lock:
            out = {}
            for name, total in self._total.items():
                rec = {
                    "count": self._count[name],
                    "total_s": round(total, 6),
                    "mean_ms": round((total / self._count[name]) * 1e3, 3)
                    if self._count[name]
                    else 0.0,
                }
                h = self._sync_hist_locked(name)
                if h is not None:
                    rec.update(h.percentiles())
                out[name] = rec
            return out

    def snapshot(self):
        """Mergeable per-stage state for the
        :class:`~blendjax.obs.hub.TelemetryHub`: ``{stage: {"count",
        "total_s", "hist"}}`` with the histograms COPIED (the hub merges
        destructively across components)."""
        with self._lock:
            return {
                name: {
                    "count": self._count[name],
                    "total_s": total,
                    "hist": (
                        self._sync_hist_locked(name).copy()
                        if name in self._hist else None
                    ),
                }
                for name, total in self._total.items()
            }

    def snapshot_serialized(self):
        """:meth:`snapshot` with histograms serialized sparse
        (``to_dict``) — the JSON-able ``stages`` shape a remote
        ``telemetry`` RPC ships and ``TelemetryHub`` remotes merge.
        One implementation for every wire-serving process (replay
        shards, policy servers)."""
        return {
            name: {
                "count": rec["count"],
                "total_s": rec["total_s"],
                "hist": (
                    rec["hist"].to_dict()
                    if rec["hist"] is not None else None
                ),
            }
            for name, rec in self.snapshot().items()
        }

    def export_chrome_trace(self, path):
        """Write recorded intervals as Chrome trace-event JSON
        (``chrome://tracing`` / Perfetto).  Requires ``trace=True``;
        raises RuntimeError otherwise.  One row per thread; timestamps are
        relative to the last :meth:`reset`."""
        if not self._trace:
            raise RuntimeError(
                "tracing is off; construct StageTimer(trace=True)"
            )
        with self._lock:
            events = list(self._events)
            origin = self._start
        pid = os.getpid()
        out = [
            {
                "name": name,
                "ph": "X",  # complete event: begin + duration
                "pid": pid,
                "tid": tid,
                "ts": (start - origin) * 1e6,  # microseconds
                "dur": dur * 1e6,
            }
            for name, start, dur, tid in events
        ]
        with open(path, "w") as f:
            json.dump({"traceEvents": out, "displayTimeUnit": "ms"}, f)
        return len(out)
