"""Lightweight per-stage timing — the observability the reference lacks
(SURVEY.md §5: "The TPU build should add lightweight stage timestamps
(render / serialize / recv / device_put) since the north-star metric is TPU
duty-cycle").

Usage::

    timer = StageTimer()
    with timer.stage("recv"):
        msg = sock.recv()
    ...
    timer.summary()   # {'recv': {'count': n, 'total_s': t, 'mean_ms': m}, ...}
    timer.duty_cycle("step")   # fraction of wall time inside 'step'
"""

from __future__ import annotations

import threading
import time
from collections import defaultdict
from contextlib import contextmanager


class StageTimer:
    """Accumulates wall-clock time per named stage (thread-safe: stages are
    recorded from loader workers and the prefetch thread concurrently)."""

    def __init__(self):
        self._lock = threading.Lock()
        self.reset()

    def reset(self):
        with getattr(self, "_lock", threading.Lock()):
            self._total = defaultdict(float)
            self._count = defaultdict(int)
            self._start = time.perf_counter()

    @contextmanager
    def stage(self, name):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.add(name, time.perf_counter() - t0)

    def add(self, name, seconds):
        with self._lock:
            self._total[name] += seconds
            self._count[name] += 1

    @property
    def wall_s(self):
        return time.perf_counter() - self._start

    def total_s(self, name):
        return self._total[name]

    def count(self, name):
        return self._count[name]

    def mean_ms(self, name):
        c = self._count[name]
        return (self._total[name] / c) * 1e3 if c else 0.0

    def duty_cycle(self, name):
        """Fraction of wall time since reset spent inside ``name``."""
        wall = self.wall_s
        return self._total[name] / wall if wall > 0 else 0.0

    def summary(self):
        return {
            name: {
                "count": self._count[name],
                "total_s": round(self._total[name], 6),
                "mean_ms": round(self.mean_ms(name), 3),
            }
            for name in self._total
        }
