// blendjax native transport: SPSC shared-memory byte ring.
//
// Same-host Blender->consumer frame transport that bypasses the tcp
// loopback path (ZMQ frame copy -> kernel send -> kernel recv -> consumer
// copy) with a single producer-side memcpy into a POSIX shm arena the
// consumer reads in place.  The reference framework has no native
// components (its hot path is pickle+tcp, SURVEY.md §0); this is the
// blendjax equivalent of owning the IPC layer natively.
//
// Layout:  [Header | byte arena]
// Records: u64 length, payload, padded to 8 bytes.  A length of
// UINT64_MAX is a wrap marker: the reader skips to the arena start.
// Single producer / single consumer, lock-free (acquire/release atomics),
// bounded: a full ring blocks the producer (same backpressure contract as
// the ZMQ HWM path, publisher.py).
//
// C ABI for ctypes; no exceptions cross the boundary.

#include <atomic>
#include <cerrno>
#include <cstdint>
#include <cstring>
#include <ctime>
#include <new>

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

constexpr uint64_t kMagic = 0x424a5852494e4701ULL;  // "BJXRING" v1
constexpr uint64_t kWrapMarker = ~0ULL;

struct Header {
  // magic is the header's publication flag: bjr_create stores it with
  // release ordering AFTER every other field is initialized, and
  // bjr_open's spin loads it with acquire — otherwise a reader could
  // observe magic == kMagic while capacity is still 0 (then compute
  // `pos % 0`, SIGFPE) on a compiler/arch that reorders the plain stores.
  std::atomic<uint64_t> magic;
  uint64_t capacity;                  // arena size in bytes (multiple of 8)
  std::atomic<uint64_t> head;         // producer: total bytes written
  std::atomic<uint64_t> tail;         // consumer: total bytes consumed
  std::atomic<uint32_t> producer_closed;
  uint32_t _pad;
};

struct Handle {
  Header* hdr;
  uint8_t* arena;
  uint64_t map_size;
  char name[256];
  int owner;          // created (vs opened)
  uint64_t last_rec;  // bytes to release after read_acquire
  uint64_t pending_commit;  // bytes reserved by bjr_write_begin, published
                            // by bjr_write_commit (zero-copy writer)
  uint64_t next_vanish_check_ms;  // rate-limits bjr_vanished's syscalls
                                  // across timeout-0 polls (hot rotation)
  dev_t st_dev;       // identity of the mapped shm object: a respawned
  ino_t st_ino;       // producer's bjr_create makes a NEW object under the
                      // same name; the reader detects the inode change
};

inline uint64_t pad8(uint64_t n) { return (n + 7) & ~7ULL; }

inline void sleep_us(unsigned us) {
  struct timespec ts = {0, static_cast<long>(us) * 1000L};
  nanosleep(&ts, nullptr);
}

inline uint64_t now_ms() {
  struct timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return static_cast<uint64_t>(ts.tv_sec) * 1000ULL + ts.tv_nsec / 1000000ULL;
}

}  // namespace

extern "C" {

// Create a ring (producer side).  capacity is rounded up to 8.
// Returns nullptr on failure.
void* bjr_create(const char* name, uint64_t capacity) {
  capacity = pad8(capacity);
  shm_unlink(name);  // stale ring from a crashed producer
  int fd = shm_open(name, O_CREAT | O_EXCL | O_RDWR, 0600);
  if (fd < 0) return nullptr;
  uint64_t map_size = sizeof(Header) + capacity;
  if (ftruncate(fd, static_cast<off_t>(map_size)) != 0) {
    close(fd);
    shm_unlink(name);
    return nullptr;
  }
  struct stat id_st;
  fstat(fd, &id_st);
  void* mem = mmap(nullptr, map_size, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  close(fd);
  if (mem == MAP_FAILED) {
    shm_unlink(name);
    return nullptr;
  }
  auto* hdr = new (mem) Header();
  hdr->capacity = capacity;
  hdr->head.store(0, std::memory_order_relaxed);
  hdr->tail.store(0, std::memory_order_relaxed);
  hdr->producer_closed.store(0, std::memory_order_relaxed);
  hdr->magic.store(kMagic, std::memory_order_release);  // published last

  auto* h = new Handle();
  h->hdr = hdr;
  h->arena = reinterpret_cast<uint8_t*>(mem) + sizeof(Header);
  h->map_size = map_size;
  std::strncpy(h->name, name, sizeof(h->name) - 1);
  h->owner = 1;
  h->last_rec = 0;
  h->st_dev = id_st.st_dev;
  h->st_ino = id_st.st_ino;
  return h;
}

// Open an existing ring (consumer side).  Waits up to timeout_ms for the
// producer to create it.  Returns nullptr on failure/timeout.
void* bjr_open(const char* name, int timeout_ms) {
  uint64_t deadline = now_ms() + static_cast<uint64_t>(timeout_ms < 0 ? 0 : timeout_ms);
  int fd = -1;
  for (;;) {
    fd = shm_open(name, O_RDWR, 0600);
    if (fd >= 0) break;
    if (timeout_ms >= 0 && now_ms() >= deadline) return nullptr;
    sleep_us(200);
  }
  struct stat st;
  while (fstat(fd, &st) == 0 &&
         st.st_size < static_cast<off_t>(sizeof(Header))) {
    if (timeout_ms >= 0 && now_ms() >= deadline) {
      close(fd);
      return nullptr;
    }
    sleep_us(200);
  }
  uint64_t map_size = static_cast<uint64_t>(st.st_size);
  void* mem = mmap(nullptr, map_size, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  close(fd);
  if (mem == MAP_FAILED) return nullptr;
  auto* hdr = reinterpret_cast<Header*>(mem);
  // acquire pairs with bjr_create's release store: capacity et al. are
  // fully visible once magic reads kMagic
  while (hdr->magic.load(std::memory_order_acquire) != kMagic) {
    if (timeout_ms >= 0 && now_ms() >= deadline) {
      munmap(mem, map_size);
      return nullptr;
    }
    sleep_us(200);
  }
  auto* h = new Handle();
  h->hdr = hdr;
  h->arena = reinterpret_cast<uint8_t*>(mem) + sizeof(Header);
  h->map_size = map_size;
  std::strncpy(h->name, name, sizeof(h->name) - 1);
  h->owner = 0;
  h->last_rec = 0;
  h->st_dev = st.st_dev;
  h->st_ino = st.st_ino;
  return h;
}

// 0: the mapped object is still what `name` resolves to.
// 1: `name` resolves to a DIFFERENT object (producer respawned, bjr_create
//    unlinked + recreated) or no longer exists (crashed, not yet back).
int bjr_vanished(void* handle) {
  auto* h = static_cast<Handle*>(handle);
  int fd = shm_open(h->name, O_RDONLY, 0600);
  if (fd < 0) return 1;
  struct stat st;
  int ok = fstat(fd, &st) == 0 && st.st_dev == h->st_dev &&
           st.st_ino == h->st_ino;
  close(fd);
  return ok ? 0 : 1;
}

namespace {

// Claim `need` contiguous bytes (record payload + 8-byte length prefix
// already included by the caller).  Returns the write position, or
// UINT64_MAX on timeout.  Handles the wrap marker.
uint64_t claim(Handle* h, uint64_t need, int timeout_ms) {
  Header* hdr = h->hdr;
  const uint64_t cap = hdr->capacity;
  uint64_t deadline = now_ms() + static_cast<uint64_t>(timeout_ms < 0 ? 0 : timeout_ms);
  uint64_t head = hdr->head.load(std::memory_order_relaxed);
  for (;;) {
    uint64_t tail = hdr->tail.load(std::memory_order_acquire);
    uint64_t pos = head % cap;
    uint64_t to_end = cap - pos;
    uint64_t total = (to_end < need) ? to_end + need : need;
    if (cap - (head - tail) >= total) {
      if (to_end < need) {
        std::memcpy(h->arena + pos, &kWrapMarker, 8);
        head += to_end;
        hdr->head.store(head, std::memory_order_release);
        pos = 0;
      }
      return pos;
    }
    if (timeout_ms >= 0 && now_ms() >= deadline) return ~0ULL;
    sleep_us(100);
  }
}

}  // namespace

// Write one record.  Blocks (bounded backpressure) until space or timeout.
// Returns 0 ok, -1 timeout, -2 message larger than ring.
int bjr_write(void* handle, const void* data, uint64_t len, int timeout_ms) {
  auto* h = static_cast<Handle*>(handle);
  const uint64_t cap = h->hdr->capacity;
  const uint64_t need = 8 + pad8(len);
  if (need + 8 > cap) return -2;  // +8: wrap marker headroom
  uint64_t pos = claim(h, need, timeout_ms);
  if (pos == ~0ULL) return -1;
  std::memcpy(h->arena + pos, &len, 8);
  std::memcpy(h->arena + pos + 8, data, len);
  h->hdr->head.fetch_add(need, std::memory_order_release);
  return 0;
}

// Scatter-gather write: one framed record assembled directly in the ring
// (no caller-side join).  Record payload layout:
//   u32 nframes | u64 len[nframes] | frame bytes (concatenated)
// This is the hot path for the Python bindings: numpy frame payloads are
// memcpy'd exactly once, from their own buffers into shm, with the GIL
// released (ctypes foreign call).
int bjr_write_v(void* handle, const void* const* bufs, const uint64_t* lens,
                uint32_t nbufs, int timeout_ms) {
  auto* h = static_cast<Handle*>(handle);
  const uint64_t cap = h->hdr->capacity;
  uint64_t payload = 4 + 8ULL * nbufs;
  for (uint32_t i = 0; i < nbufs; ++i) payload += lens[i];
  const uint64_t need = 8 + pad8(payload);
  if (need + 8 > cap) return -2;
  uint64_t pos = claim(h, need, timeout_ms);
  if (pos == ~0ULL) return -1;
  uint8_t* p = h->arena + pos;
  std::memcpy(p, &payload, 8);
  p += 8;
  std::memcpy(p, &nbufs, 4);
  p += 4;
  std::memcpy(p, lens, 8ULL * nbufs);
  p += 8ULL * nbufs;
  for (uint32_t i = 0; i < nbufs; ++i) {
    std::memcpy(p, bufs[i], lens[i]);
    p += lens[i];
  }
  h->hdr->head.fetch_add(need, std::memory_order_release);
  return 0;
}

// Zero-copy writer: reserve space for one record of `len` payload bytes
// and return a pointer to the payload start (the caller assembles the
// record IN the arena — e.g. a columnar gather lands its batch directly
// in shared memory, skipping the staging copy bjr_write_v would pay).
// The record is invisible to the reader until bjr_write_commit publishes
// it.  Returns nullptr on timeout or when the record cannot fit at all
// (the caller distinguishes by checking the size against the capacity
// up front).  One reservation may be outstanding per handle.
void* bjr_write_begin(void* handle, uint64_t len, int timeout_ms) {
  auto* h = static_cast<Handle*>(handle);
  const uint64_t cap = h->hdr->capacity;
  const uint64_t need = 8 + pad8(len);
  if (need + 8 > cap) return nullptr;
  uint64_t pos = claim(h, need, timeout_ms);
  if (pos == ~0ULL) return nullptr;
  std::memcpy(h->arena + pos, &len, 8);
  h->pending_commit = need;
  return h->arena + pos + 8;
}

void bjr_write_commit(void* handle) {
  auto* h = static_cast<Handle*>(handle);
  if (h->pending_commit) {
    h->hdr->head.fetch_add(h->pending_commit, std::memory_order_release);
    h->pending_commit = 0;
  }
}

// Acquire the next record without copying.  *data points into the shm
// arena and stays valid until bjr_read_release.  Returns 0 ok, -1 timeout,
// -3 producer closed and ring drained, -4 ring vanished/recreated under
// this mapping (producer crashed or was respawned; reopen to continue).
// Buffered records are always drained before -4 is reported — a crash
// mid-write is invisible (head only advances after a complete record).
int bjr_read_acquire(void* handle, const void** data, uint64_t* len,
                     int timeout_ms) {
  auto* h = static_cast<Handle*>(handle);
  Header* hdr = h->hdr;
  const uint64_t cap = hdr->capacity;
  uint64_t deadline = now_ms() + static_cast<uint64_t>(timeout_ms < 0 ? 0 : timeout_ms);
  uint64_t next_vanish_check = now_ms() + 50;

  for (;;) {
    uint64_t tail = hdr->tail.load(std::memory_order_relaxed);
    uint64_t head = hdr->head.load(std::memory_order_acquire);
    if (head != tail) {
      uint64_t pos = tail % cap;
      uint64_t rec_len;
      std::memcpy(&rec_len, h->arena + pos, 8);
      if (rec_len == kWrapMarker) {
        hdr->tail.store(tail + (cap - pos), std::memory_order_release);
        continue;
      }
      *data = h->arena + pos + 8;
      *len = rec_len;
      h->last_rec = 8 + pad8(rec_len);
      return 0;
    }
    if (hdr->producer_closed.load(std::memory_order_acquire)) return -3;
    if (timeout_ms >= 0 && now_ms() >= deadline) {
      // Vanish must be detectable even at timeout_ms == 0: the multi-ring
      // rotation polls with 0 and would otherwise never learn that a
      // respawned producer recreated the ring (stale mapping polled
      // forever, returning -1 until the dataset times out).  The check is
      // rate-limited via the handle (~50 ms cadence) so steady-state idle
      // polls don't pay shm_open+fstat per call; healing latency stays
      // bounded at the cadence.
      if (!h->owner && now_ms() >= h->next_vanish_check_ms) {
        h->next_vanish_check_ms = now_ms() + 50;
        if (bjr_vanished(handle)) return -4;
      }
      return -1;
    }
    if (!h->owner && now_ms() >= next_vanish_check) {
      if (bjr_vanished(handle)) return -4;
      next_vanish_check = now_ms() + 50;
    }
    sleep_us(100);
  }
}

void bjr_read_release(void* handle) {
  auto* h = static_cast<Handle*>(handle);
  if (h->last_rec) {
    h->hdr->tail.fetch_add(h->last_rec, std::memory_order_release);
    h->last_rec = 0;
  }
}

// Number of unread bytes currently buffered (diagnostics).
uint64_t bjr_pending(void* handle) {
  auto* h = static_cast<Handle*>(handle);
  return h->hdr->head.load(std::memory_order_acquire) -
         h->hdr->tail.load(std::memory_order_acquire);
}

void bjr_close(void* handle, int unlink_shm) {
  auto* h = static_cast<Handle*>(handle);
  if (h->owner) h->hdr->producer_closed.store(1, std::memory_order_release);
  munmap(reinterpret_cast<void*>(h->hdr), h->map_size);
  if (unlink_shm) shm_unlink(h->name);
  delete h;
}

// Batch assembly: copy n equal-role source buffers back-to-back into dst.
// ctypes releases the GIL for the duration of the call, so concurrent
// loader workers collate truly in parallel (np.stack holds the GIL for the
// whole copy, serializing every worker thread through one core).
void bjr_gather(char* dst, const void* const* srcs, const uint64_t* lens,
                uint64_t n) {
  uint64_t off = 0;
  for (uint64_t i = 0; i < n; ++i) {
    memcpy(dst + off, srcs[i], lens[i]);
    off += lens[i];
  }
}

// --- test support (tsan_stress.cpp) -----------------------------------
// Alias a reader Handle onto an EXISTING mapping.  ThreadSanitizer keys
// its shadow state on virtual addresses: a second mmap of the same shm
// object would give the reader a disjoint range and hide every
// cross-thread access pair from the tool, so the stress harness reads
// through the writer's own mapping.  The alias does not own the mapping
// — free it with bjr_test_free_alias, never bjr_close.
void* bjr_test_alias_reader(void* handle) {
  auto* src = static_cast<Handle*>(handle);
  auto* h = new Handle(*src);
  h->owner = 0;
  h->last_rec = 0;
  h->next_vanish_check_ms = 0;
  return h;
}

void bjr_test_free_alias(void* handle) {
  delete static_cast<Handle*>(handle);
}

}  // extern "C"
