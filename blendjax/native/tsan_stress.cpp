// ThreadSanitizer stress for the SPSC shm ring (ringbuf.cpp).
//
// TSAN keys its shadow state on VIRTUAL addresses, so a reader that
// mmap()s the shm object separately (as a real cross-process consumer
// does) is invisible to the tool — every cross-thread pair would go
// unchecked and the harness would pass vacuously.  The reader here
// therefore runs through an ALIAS of the writer's own mapping
// (bjr_test_alias_reader): one address range, both sides of every
// happens-before edge instrumented.
//
// Scope: the SPSC protocol itself — head/tail publication, wrap markers,
// payload visibility, backpressure — across several ring generations
// (create -> stream -> drain -> close -> recreate).  The create/open
// *handshake* across two mappings is not TSAN-instrumentable by nature;
// its publication ordering is enforced directly in the code
// (Header::magic release/acquire, see ringbuf.cpp).
//
// Build + run: `make -C blendjax/native tsan-stress` (exit 0 + no TSAN
// report = pass).  Driven by tests/test_ring_stress.py.

#include <atomic>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <thread>
#include <sys/mman.h>
#include <unistd.h>

extern "C" {
void* bjr_create(const char* name, uint64_t capacity);
int bjr_write(void* handle, const void* data, uint64_t len, int timeout_ms);
int bjr_read_acquire(void* handle, const void** data, uint64_t* len,
                     int timeout_ms);
void bjr_read_release(void* handle);
uint64_t bjr_pending(void* handle);
void bjr_close(void* handle, int unlink_shm);
void* bjr_test_alias_reader(void* handle);
void bjr_test_free_alias(void* handle);
}

namespace {

constexpr int kGenerations = 4;
constexpr uint64_t kPerGen = 4000;
constexpr uint64_t kCap = 1 << 16;  // small ring: constant wrap pressure

const char* kName = nullptr;

std::atomic<void*> g_writer_handle{nullptr};
std::atomic<int> g_pub_gen{-1};  // generation whose handle is published
std::atomic<int> g_ack_gen{-1};  // last generation fully drained by reader
std::atomic<bool> fail{false};

void check(bool ok, const char* what) {
  if (!ok) {
    std::fprintf(stderr, "FAIL: %s\n", what);
    fail.store(true);
  }
}

void writer() {
  for (int gen = 0; gen < kGenerations; ++gen) {
    void* h = bjr_create(kName, kCap);
    check(h != nullptr, "bjr_create");
    if (!h) return;
    g_writer_handle.store(h, std::memory_order_release);
    g_pub_gen.store(gen, std::memory_order_release);
    // varied record sizes: wrap-marker and padding paths under load; the
    // small capacity keeps writer and reader in constant contention on
    // head/tail while payload memcpys race the reader's copy-outs
    unsigned char buf[1500];
    for (uint64_t i = 0; i < kPerGen; ++i) {
      uint64_t stamp[2] = {static_cast<uint64_t>(gen), i};
      std::memcpy(buf, stamp, 16);
      uint64_t len = 16 + (i * 37) % (sizeof(buf) - 16);
      int rc = bjr_write(h, buf, len, 5000);
      check(rc == 0, "bjr_write");
      if (rc != 0) break;
    }
    // the reader aliases THIS mapping: close (munmap) strictly after the
    // reader acked the generation — it acks on failure paths too, so
    // waiting on the ack alone can neither deadlock nor munmap pages the
    // reader is still dereferencing
    while (g_ack_gen.load(std::memory_order_acquire) < gen) {
      usleep(100);
    }
    bjr_close(h, /*unlink_shm=*/1);
    if (fail.load()) return;
  }
}

void reader() {
  for (int gen = 0; gen < kGenerations; ++gen) {
    while (g_pub_gen.load(std::memory_order_acquire) < gen) {
      if (fail.load()) return;  // writer aborted: nothing will be published
      usleep(100);
    }
    void* alias =
        bjr_test_alias_reader(g_writer_handle.load(std::memory_order_acquire));
    uint64_t got = 0;
    while (got < kPerGen) {
      const void* data = nullptr;
      uint64_t len = 0;
      int rc = bjr_read_acquire(alias, &data, &len, 2000);
      if (rc == -1) {
        check(false, "reader starved (writer stalled?)");
        break;
      }
      check(rc == 0, "bjr_read_acquire");
      if (rc != 0) break;
      check(len >= 16, "record length");
      uint64_t stamp[2];
      std::memcpy(stamp, data, 16);
      check(stamp[0] == static_cast<uint64_t>(gen), "generation stamp");
      check(stamp[1] == got, "SPSC lost or reordered a record");
      (void)bjr_pending(alias);  // concurrent head load vs writer stores
      bjr_read_release(alias);
      ++got;
    }
    bjr_test_free_alias(alias);
    g_ack_gen.store(gen, std::memory_order_release);
    if (fail.load()) return;
  }
  std::fprintf(stderr, "reader drained %d generations x %llu records\n",
               kGenerations, static_cast<unsigned long long>(kPerGen));
}

}  // namespace

int main() {
  char name[128];
  std::snprintf(name, sizeof(name), "bjx-tsan-stress-%d", getpid());
  kName = name;
  std::thread w(writer);
  std::thread r(reader);
  w.join();
  r.join();
  shm_unlink(name);
  if (fail.load()) return 1;
  std::puts("tsan stress ok");
  return 0;
}
