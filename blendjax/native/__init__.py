"""Native (C++) components.  Built on demand with the bundled Makefile;
everything here is optional — the pure-ZMQ paths work without it."""

from blendjax.native.ring import (  # noqa: F401
    DoorBell,
    ShmRingReader,
    ShmRingWriter,
    copy_into,
    fast_stack,
    gather_into,
    is_shm_address,
    native_available,
    shm_name_from_address,
    unlink_address,
)
