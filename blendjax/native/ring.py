"""ctypes bindings for the native SPSC shared-memory ring
(``ringbuf.cpp``) + message framing shared with the wire protocol.

Address scheme: ``shm://<name>`` — accepted directly by
:class:`blendjax.btb.publisher.DataPublisher` (writer side binds/creates)
and :class:`blendjax.btt.dataset.RemoteIterableDataset` (reader side
opens).  The launcher allocates these like tcp addresses when
``proto='shm'``.

Message framing inside a ring record re-uses the multipart wire encoding
(:func:`blendjax.wire.encode`): ``u32 nframes``, then per frame ``u64 len``
+ bytes.  Arrays decode as views into the shm arena and are copied out
before release (one copy total; the tcp path costs a pickle copy + two
kernel copies).

The .so builds on first use via the bundled Makefile (g++); if no compiler
is available, ``native_available()`` returns False and callers should fall
back to tcp.
"""

from __future__ import annotations

import ctypes
import os
import struct
import subprocess
import threading

_DIR = os.path.dirname(os.path.abspath(__file__))
_SO = os.path.join(_DIR, "libblendjax_ring.so")
_BUILD_LOCK = threading.Lock()
_LIB = None
_LIB_ERR = None


def _load():
    global _LIB, _LIB_ERR
    if _LIB is not None or _LIB_ERR is not None:
        return _LIB
    with _BUILD_LOCK:
        if _LIB is not None or _LIB_ERR is not None:
            return _LIB
        src = os.path.join(_DIR, "ringbuf.cpp")
        stale = not os.path.exists(_SO) or (
            os.path.exists(src)
            and os.path.getmtime(src) > os.path.getmtime(_SO)
        )
        if stale:
            try:
                # Makefile builds to a temp name and renames atomically, so
                # concurrent builders never expose a half-written .so
                subprocess.run(
                    ["make", "-s"], cwd=_DIR, check=True, capture_output=True
                )
            except (OSError, subprocess.CalledProcessError) as e:
                if not os.path.exists(_SO):
                    _LIB_ERR = e
                    return None
                # no toolchain but a prebuilt .so exists: use it
        try:
            lib = ctypes.CDLL(_SO)
        except OSError as e:
            _LIB_ERR = e
            return None
        try:
            _bind(lib)
        except AttributeError as e:
            # A prebuilt .so from an older build can lack newer symbols
            # (e.g. bjr_gather); treat it as unavailable so callers
            # degrade to the tcp path instead of raising on every call.
            _LIB_ERR = e
            return None
        _LIB = lib
        return _LIB


def _bind(lib):
    lib.bjr_create.restype = ctypes.c_void_p
    lib.bjr_create.argtypes = [ctypes.c_char_p, ctypes.c_uint64]
    lib.bjr_open.restype = ctypes.c_void_p
    lib.bjr_open.argtypes = [ctypes.c_char_p, ctypes.c_int]
    lib.bjr_write.restype = ctypes.c_int
    lib.bjr_write.argtypes = [
        ctypes.c_void_p,
        ctypes.c_char_p,
        ctypes.c_uint64,
        ctypes.c_int,
    ]
    lib.bjr_write_v.restype = ctypes.c_int
    lib.bjr_write_v.argtypes = [
        ctypes.c_void_p,
        ctypes.POINTER(ctypes.c_void_p),
        ctypes.POINTER(ctypes.c_uint64),
        ctypes.c_uint32,
        ctypes.c_int,
    ]
    lib.bjr_read_acquire.restype = ctypes.c_int
    lib.bjr_read_acquire.argtypes = [
        ctypes.c_void_p,
        ctypes.POINTER(ctypes.c_void_p),
        ctypes.POINTER(ctypes.c_uint64),
        ctypes.c_int,
    ]
    lib.bjr_read_release.argtypes = [ctypes.c_void_p]
    lib.bjr_vanished.restype = ctypes.c_int
    lib.bjr_vanished.argtypes = [ctypes.c_void_p]
    lib.bjr_pending.restype = ctypes.c_uint64
    lib.bjr_pending.argtypes = [ctypes.c_void_p]
    lib.bjr_close.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.bjr_gather.restype = None
    lib.bjr_gather.argtypes = [
        ctypes.c_void_p,
        ctypes.POINTER(ctypes.c_void_p),
        ctypes.POINTER(ctypes.c_uint64),
        ctypes.c_uint64,
    ]
    # zero-copy writer (bjr_write_begin/commit): OPTIONAL — a prebuilt
    # .so from an older source may lack it, and that must not take the
    # whole native layer down (the feed path needs none of it)
    try:
        lib.bjr_write_begin.restype = ctypes.c_void_p
        lib.bjr_write_begin.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64, ctypes.c_int,
        ]
        lib.bjr_write_commit.argtypes = [ctypes.c_void_p]
        lib.bjx_has_write_begin = True
    except AttributeError:
        lib.bjx_has_write_begin = False


def native_available() -> bool:
    return _load() is not None


def is_shm_address(address: str) -> bool:
    return isinstance(address, str) and address.startswith("shm://")


def shm_name_from_address(address: str) -> str:
    name = address[len("shm://"):]
    return name if name.startswith("/") else "/" + name


def _frame_ptr_len(obj):
    """(pointer, nbytes, keepalive) for a frame without copying.

    numpy arrays expose their data pointer directly; bytes via c_char_p.
    Anything else is materialized to bytes once.
    """
    import numpy as np

    if isinstance(obj, np.ndarray):
        arr = np.ascontiguousarray(obj)
        return arr.ctypes.data, arr.nbytes, arr
    if not isinstance(obj, (bytes, bytearray)):
        obj = bytes(obj)
    buf = (ctypes.c_char * len(obj)).from_buffer_copy(obj) if isinstance(
        obj, bytearray
    ) else obj
    if isinstance(buf, bytes):
        ptr = ctypes.cast(ctypes.c_char_p(buf), ctypes.c_void_p).value
        return ptr, len(buf), buf
    return ctypes.addressof(buf), len(obj), buf


#: Payload frames at or above this size copy out of the shm arena via the
#: native GIL-released memcpy; smaller ones use ``bytes`` (lower overhead).
_NATIVE_COPY_MIN_BYTES = 64 * 1024


def _split_record(buf: memoryview):
    """Parse a record written by ``bjr_write_v`` —
    ``u32 nframes | u64 len[n] | payloads`` — into (offset, length) pairs.
    The single source of truth for the record framing."""
    (nframes,) = struct.unpack_from("<I", buf, 0)
    lens = struct.unpack_from(f"<{nframes}Q", buf, 4)
    off = 4 + 8 * nframes
    spans = []
    for ln in lens:
        spans.append((off, ln))
        off += ln
    return spans


def _unpack_frames(lib, base_addr: int, buf: memoryview):
    """Copy a record's payloads out of the arena, exactly once each —
    large frames via ``bjr_gather`` with the GIL released (k loader
    threads copy on k cores), small ones via ``bytes``.
    """
    import numpy as np

    frames = []
    for off, ln in _split_record(buf):
        if ln >= _NATIVE_COPY_MIN_BYTES:
            out = np.empty(ln, np.uint8)
            ptrs = (ctypes.c_void_p * 1)(base_addr + off)
            lns = (ctypes.c_uint64 * 1)(ln)
            lib.bjr_gather(out.ctypes.data_as(ctypes.c_void_p), ptrs, lns, 1)
            frames.append(out)
        else:
            frames.append(bytes(buf[off : off + ln]))
    return frames


class ShmRingWriter:
    """Producer end of a shm ring (DataPublisher backend)."""

    def __init__(self, address, capacity_bytes=64 << 20):
        lib = _load()
        if lib is None:
            raise RuntimeError(
                f"native ring unavailable (build failed: {_LIB_ERR}); use tcp"
            )
        self._lib = lib
        self.capacity_bytes = int(capacity_bytes)
        name = shm_name_from_address(address)
        self._h = lib.bjr_create(name.encode(), capacity_bytes)
        if not self._h:
            raise OSError(f"failed to create shm ring {name}")

    def send_frames(self, frames, timeout_ms=-1) -> bool:
        """Write one framed message; False on timeout (backpressure).

        Scatter-gather: each frame (numpy array or bytes) is memcpy'd once,
        directly into the shm arena by ``bjr_write_v`` with the GIL
        released — no Python-side join.
        """
        if self._h is None:
            # a closed writer must fail as an I/O error, not hand the
            # native layer a NULL handle (instant segfault): an RPC
            # server can race a reply against its own channel drop
            raise OSError("shm ring writer is closed")
        n = len(frames)
        ptrs = (ctypes.c_void_p * n)()
        lens = (ctypes.c_uint64 * n)()
        keep = []
        for i, f in enumerate(frames):
            ptr, ln, alive = _frame_ptr_len(f)
            ptrs[i] = ptr
            lens[i] = ln
            keep.append(alive)
        rc = self._lib.bjr_write_v(self._h, ptrs, lens, n, timeout_ms)
        del keep
        if rc == -2:
            raise ValueError("message larger than ring capacity")
        return rc == 0

    def begin_record(self, nbytes, timeout_ms=-1):
        """Reserve a ``nbytes`` record and return a writable ``uint8``
        view INTO the ring arena (the zero-copy writer: a columnar
        gather lands its batch straight in shared memory, skipping the
        staging copy :meth:`send_frames` would pay).  The record is
        invisible to the reader until :meth:`commit_record`.  Returns
        None on timeout or when the native layer predates the API;
        raises ValueError when the record cannot fit the ring at all.
        """
        import numpy as np

        if self._h is None:
            raise OSError("shm ring writer is closed")
        if not getattr(self._lib, "bjx_has_write_begin", False):
            return None
        padded = (nbytes + 7) & ~7
        if 8 + padded + 8 > self.capacity_bytes:
            raise ValueError("message larger than ring capacity")
        ptr = self._lib.bjr_write_begin(self._h, nbytes, timeout_ms)
        if not ptr:
            return None
        buf = (ctypes.c_char * nbytes).from_address(ptr)
        return np.frombuffer(buf, np.uint8)

    def commit_record(self):
        """Publish the record reserved by :meth:`begin_record`."""
        if self._h is None:
            raise OSError("shm ring writer is closed")
        self._lib.bjr_write_commit(self._h)

    def pending_bytes(self):
        return 0 if self._h is None else self._lib.bjr_pending(self._h)

    def close(self, unlink=True):
        if self._h:
            self._lib.bjr_close(self._h, int(unlink))
            self._h = None


class ShmRingReader:
    """Consumer end of a shm ring (dataset backend).

    Elasticity: a producer that crashes and is respawned (e.g. by
    :class:`blendjax.btt.watchdog.FleetWatchdog`) recreates the ring under
    the same name — a new shm object the old mapping cannot see.  The
    native layer detects the identity change (rc -4) and, with
    ``auto_reopen`` (default), the reader transparently remaps the new
    generation and keeps streaming; ``reconnects`` counts generations for
    observability.  In-flight records of the dead generation that were
    fully written are drained first; partially-written ones were never
    visible (head publishes only complete records).

    ``poison=True`` (or ``BJX_SHM_POISON=1``) arms the use-after-release
    guard on :meth:`recv_frames_view`: :meth:`release_record` releases
    the handed-out memoryviews, so any access to a view after its ring
    slot was freed raises ``ValueError`` instead of silently reading
    bytes the producer may already be overwriting.
    """

    def __init__(self, address, open_timeout_ms=10000, auto_reopen=True,
                 poison=None):
        lib = _load()
        if lib is None:
            raise RuntimeError(
                f"native ring unavailable (build failed: {_LIB_ERR}); use tcp"
            )
        self._lib = lib
        self._name = shm_name_from_address(address)
        self._auto_reopen = auto_reopen
        self._open_timeout_ms = open_timeout_ms
        self._poison = (
            os.environ.get("BJX_SHM_POISON", "") == "1"
            if poison is None else bool(poison)
        )
        self._out_views = None  # views handed out by recv_frames_view
        self.reconnects = 0
        self._h = lib.bjr_open(self._name.encode(), open_timeout_ms)
        if not self._h:
            raise OSError(f"failed to open shm ring {self._name}")

    def _acquire(self, data, length, timeout_ms):
        """read_acquire with vanished-ring reopen inside the deadline.

        ``timeout_ms < 0`` means wait forever (matching the C layer's
        convention): reopen attempts then loop on ``open_timeout_ms``
        slices with no deadline.  After a failed reopen the reader stays
        retryable — ``_h`` is None and the next call resumes the reopen
        instead of dereferencing a dead handle.
        """
        import time

        infinite = timeout_ms < 0
        deadline = None if infinite else time.monotonic() + timeout_ms / 1e3

        def remaining_ms():
            return -1 if infinite else int((deadline - time.monotonic()) * 1e3)

        while True:
            if self._h is None:
                # a previous generation vanished; always make at least one
                # (possibly non-blocking) reopen attempt — the timeout-0
                # rotation path heals exactly this way, one attempt per
                # sweep until the respawned producer's ring appears
                wait = self._open_timeout_ms if infinite else max(remaining_ms(), 0)
                h = self._lib.bjr_open(self._name.encode(), wait)
                if not h:
                    if infinite:
                        continue
                    raise ConnectionResetError(
                        f"shm ring {self._name} vanished; reopen timed out"
                    )
                self._h = h
                self.reconnects += 1
            rc = self._lib.bjr_read_acquire(
                self._h,
                ctypes.byref(data),
                ctypes.byref(length),
                -1 if infinite else max(remaining_ms(), 0),
            )
            if rc != -4:
                return rc
            if not self._auto_reopen:
                raise ConnectionResetError(
                    f"shm ring {self._name} vanished (producer died)"
                )
            self._lib.bjr_close(self._h, 0)
            self._h = None

    def recv_frames(self, timeout_ms):
        """Next framed message as a list of buffer-like frames, or None on
        timeout.

        Small frames are ``bytes``; frames >= 64 KiB are 1-D ``np.uint8``
        arrays (copied out of the arena with the GIL released).  Consumers
        must treat frames as buffers (``memoryview``-compatible), not as
        ``bytes`` specifically — :func:`blendjax.wire.decode` does.

        Raises EOFError when the producer closed and the ring is drained,
        ConnectionResetError when the producer vanished and did not come
        back within the timeout.
        """
        data = ctypes.c_void_p()
        length = ctypes.c_uint64()
        rc = self._acquire(data, length, timeout_ms)
        if rc == -1:
            return None
        if rc == -3:
            raise EOFError("producer closed")
        try:
            buf = (ctypes.c_char * length.value).from_address(data.value)
            return _unpack_frames(self._lib, data.value, memoryview(buf))
        finally:
            self._lib.bjr_read_release(self._h)

    def recv_frames_view(self, timeout_ms):
        """Zero-copy variant of :meth:`recv_frames`: frames are memoryviews
        **into the shm arena**, valid only until :meth:`release_record` —
        which MUST be called before the next recv (it frees the ring slot;
        the producer may be blocked on it).  Use when the payload is copied
        exactly once into its final destination (e.g. a preallocated batch
        buffer) instead of through an intermediate frame buffer.
        """
        data = ctypes.c_void_p()
        length = ctypes.c_uint64()
        rc = self._acquire(data, length, timeout_ms)
        if rc == -1:
            return None
        if rc == -3:
            raise EOFError("producer closed")
        buf = (ctypes.c_char * length.value).from_address(data.value)
        mv = memoryview(buf)
        views = [mv[off : off + ln] for off, ln in _split_record(mv)]
        if self._poison:
            self._out_views = views + [mv]
        return views

    def release_record(self):
        """Release the record handed out by :meth:`recv_frames_view`.
        With poisoning armed, the handed-out views are released too, so
        a caller that kept one past this point gets ``ValueError`` on
        its next access instead of bytes a later producer write may
        already have clobbered."""
        if self._out_views is not None:
            views, self._out_views = self._out_views, None
            for v in views:
                try:
                    v.release()
                except BufferError:
                    # an np.frombuffer (or similar) still exports this
                    # view's buffer — Python cannot revoke an exported
                    # buffer, so such a view stays un-poisoned (the
                    # arrays built over it must be copied out before
                    # release, same contract as the views themselves)
                    pass
        if self._h is not None:
            self._lib.bjr_read_release(self._h)

    def pending_bytes(self):
        # _h is None between a failed reopen and the next recv retry; a
        # dead generation has nothing pending
        return 0 if self._h is None else self._lib.bjr_pending(self._h)

    def close(self, unlink=False):
        if self._h:
            self._lib.bjr_close(self._h, int(unlink))
            self._h = None
        elif unlink:
            # handle already gone (failed reopen); still honor the unlink
            _unlink_name(self._name)


def _unlink_name(name):
    """Best-effort removal of a shm object by name (POSIX shm objects live
    under /dev/shm on Linux)."""
    try:
        os.unlink(os.path.join("/dev/shm", name.lstrip("/")))
    except OSError:
        pass


def unlink_address(address):
    """Best-effort removal of a ring's shm backing file."""
    _unlink_name(shm_name_from_address(address))


class DoorBell:
    """A ``select()``-able wakeup line next to a shm ring: a named FIFO
    under ``/dev/shm`` the ring WRITER dings after publishing a record,
    so the reading process can park in one ``poll``/``select`` covering
    its ZMQ sockets AND its shm rings instead of sleep-polling the ring
    (the C layer's 100 µs nanosleep loop stays as the fallback when no
    bell is attached).  FIFOs are the portable fd-shaped doorbell here —
    unlike an eventfd they rendezvous by NAME across unrelated
    processes, and unlike a futex they compose with ``zmq.Poller``.

    Owner side (reader)::

        bell = DoorBell(path, create=True)   # mkfifo + open read end
        poller.register(bell.fd, zmq.POLLIN)
        ...
        bell.drain()                         # consume pending dings

    Remote side (writer)::

        bell = DoorBell(path)                # open write end lazily
        ring_writer.send_frames(frames)
        bell.ding()

    A ding can never be lost between a reader's empty-ring check and its
    park: the writer publishes the record BEFORE dinging, and the byte
    stays readable until drained — so ``select`` returns immediately if
    the ding already happened.  All failure modes (no reader yet, pipe
    full, reader gone) degrade to "no wakeup", which the reader's
    bounded poll timeout covers.
    """

    def __init__(self, path, create=False):
        self.path = path
        self.owner = bool(create)
        self.fd = None
        self._wfd = None
        if create:
            try:
                os.unlink(path)  # stale bell from a crashed predecessor
            except OSError:
                pass
            os.mkfifo(path, 0o600)
            # O_RDWR instead of O_RDONLY: keeps a write end open inside
            # this process, so writers never race ENXIO against the
            # reader and the fd never signals EOF-readable when the
            # last remote writer closes
            self.fd = os.open(path, os.O_RDWR | os.O_NONBLOCK)

    def ding(self):
        """One wakeup byte, best-effort (never blocks, never raises)."""
        try:
            if self._wfd is None:
                self._wfd = os.open(self.path, os.O_WRONLY | os.O_NONBLOCK)
            os.write(self._wfd, b"\x00")
        except OSError:
            # ENXIO (no reader yet), EAGAIN (pipe full: the reader is
            # awake and behind — a wakeup is already pending), or the
            # bell vanished: the reader's poll timeout covers all three
            if self._wfd is not None:
                try:
                    os.close(self._wfd)
                except OSError:
                    pass
                self._wfd = None

    def drain(self):
        """Consume pending dings (owner side), returning the byte count."""
        total = 0
        while self.fd is not None:
            try:
                got = os.read(self.fd, 4096)
            except (BlockingIOError, OSError):
                break
            if not got:
                break
            total += len(got)
        return total

    def close(self, unlink=None):
        for attr in ("fd", "_wfd"):
            f = getattr(self, attr)
            if f is not None:
                try:
                    os.close(f)
                except OSError:
                    pass
                setattr(self, attr, None)
        if unlink if unlink is not None else self.owner:
            try:
                os.unlink(self.path)
            except OSError:
                pass


def copy_into(dst, src):
    """memcpy one C-contiguous ndarray (or view) into another, GIL released
    for large payloads.  Shapes/dtypes must already match; ``dst`` must be
    C-contiguous (a leading-axis batch slot qualifies)."""
    import numpy as np

    lib = _load()
    if (
        lib is None
        or dst.nbytes < _NATIVE_COPY_MIN_BYTES
        or dst.dtype.hasobject
        or not (dst.flags["C_CONTIGUOUS"] and src.flags["C_CONTIGUOUS"])
    ):
        np.copyto(dst, src)
        return
    ptrs = (ctypes.c_void_p * 1)(src.ctypes.data)
    lens = (ctypes.c_uint64 * 1)(src.nbytes)
    lib.bjr_gather(dst.ctypes.data_as(ctypes.c_void_p), ptrs, lens, 1)


def _src_ptr_len(obj):
    """(pointer, nbytes, keepalive) for a read-only source buffer.

    ndarrays expose their data pointer directly (non-contiguous ones are
    compacted once); anything buffer-like (memoryview into a ZMQ frame or
    shm record, bytes) goes through a zero-copy ``np.frombuffer`` view,
    which also keeps the underlying buffer alive for the call.
    """
    import numpy as np

    if isinstance(obj, np.ndarray):
        if not obj.flags["C_CONTIGUOUS"]:
            obj = np.ascontiguousarray(obj)
        return obj.ctypes.data, obj.nbytes, obj
    arr = np.frombuffer(obj, np.uint8)
    return arr.ctypes.data, arr.nbytes, arr


def gather_into(dst, srcs):
    """Copy ``srcs`` (buffers/ndarrays) back-to-back into ``dst``, GIL
    released — ONE native call per batch leaf instead of one Python-level
    copy per sample, so large-frame scatters overlap with the recv thread
    and with other loader workers.

    ``dst`` must be a C-contiguous ndarray whose total bytes equal the
    summed source bytes (the batch-assembly contract: ``dst`` is an
    arena leaf ``(n, *shape)`` and ``srcs`` are the n per-sample
    payloads).  Falls back to numpy slice copies when the native library
    is unavailable.
    """
    import numpy as np

    if not dst.flags["C_CONTIGUOUS"] or dst.dtype.hasobject:
        raise ValueError("gather_into requires a C-contiguous non-object dst")
    n = len(srcs)
    ptrs = (ctypes.c_void_p * n)()
    lens = (ctypes.c_uint64 * n)()
    keep = []
    total = 0
    for i, s in enumerate(srcs):
        ptr, ln, alive = _src_ptr_len(s)
        ptrs[i] = ptr
        lens[i] = ln
        total += ln
        keep.append(alive)
    if total != dst.nbytes:
        raise ValueError(
            f"source bytes {total} != destination bytes {dst.nbytes}"
        )
    lib = _load()
    if lib is None:
        flat = dst.reshape(-1).view(np.uint8)
        off = 0
        for alive in keep:
            ln = alive.nbytes
            flat[off : off + ln] = alive.reshape(-1).view(np.uint8)
            off += ln
    elif n:
        lib.bjr_gather(dst.ctypes.data_as(ctypes.c_void_p), ptrs, lens, n)
    del keep
    return dst


def fast_stack(items, out=None):
    """Stack equal-shape ndarrays on a new leading axis, GIL released.

    ``np.stack`` holds the GIL for the whole copy, so concurrent
    :class:`blendjax.btt.loader.BatchLoader` workers serialize their
    collation through one core.  This variant memcpys each source into the
    preallocated batch buffer via the native ``bjr_gather``
    (:func:`gather_into`); ctypes drops the GIL for the call, so k loader
    threads collate on k cores.  Falls back to ``np.stack`` when the
    native library is unavailable.
    """
    import numpy as np

    first = items[0]
    n = len(items)
    for a in items[1:]:
        if a.shape != first.shape or a.dtype != first.dtype:
            raise ValueError("fast_stack requires equal shapes and dtypes")
    if _load() is None or first.dtype.hasobject:
        # object dtypes hold PyObject pointers: a raw memcpy would skip the
        # increfs and corrupt refcounts
        return np.stack(items, out=out)
    if out is None:
        out = np.empty((n,) + first.shape, dtype=first.dtype)
    elif (
        out.shape != (n,) + first.shape
        or out.dtype != first.dtype
        or not out.flags["C_CONTIGUOUS"]
    ):
        raise ValueError(
            f"out must be C-contiguous with shape {(n,) + first.shape} and "
            f"dtype {first.dtype}, got {out.shape} {out.dtype}"
        )
    return gather_into(out, items)
