# blendjax developer entry points.
#
# `make blender-tests` is the one-command real-Blender acceptance run
# (VERDICT r2 task #6): on any machine with a Blender binary it needs no
# edits — discovery walks $PATH (override with $BLENDJAX_REAL_BLENDER);
# headless hosts get a GL context via scripts/blender_headless.sh.

PYTHON ?= python
# tier1 uses pipefail/PIPESTATUS (bash); everything else is sh-safe too
SHELL := /bin/bash

.PHONY: test tier1 chaos chaos-replay chaos-learner chaos-autoscale \
	chaos-pipeline blender-tests \
	tpu-tests bench rlbench rlbench-sharded replaybench shmbench \
	servebench gatewaybench weightbench scenariobench habench \
	autoscalebench pipebench multichip dryrun benchdiff obsdemo

test:
	# env -u: the axon sitecustomize trigger makes `import jax` dial the
	# TPU tunnel relay; tests are CPU-only and must survive a dead relay
	# (conftest.py strips it for child processes; the pytest interpreter
	# itself must start without it)
	env -u PALLAS_AXON_POOL_IPS $(PYTHON) -m pytest tests/ -q

# The ROADMAP tier-1 verify command, verbatim: CPU-forced, non-slow
# subset with the driver's DOTS_PASSED accounting.  This is the gate a
# PR must keep no worse than the seed.
tier1:
	set -o pipefail; rm -f /tmp/_t1.log; \
	timeout -k 10 870 env JAX_PLATFORMS=cpu $(PYTHON) -m pytest tests/ -q \
		-m 'not slow' --continue-on-collection-errors \
		-p no:cacheprovider -p no:xdist -p no:randomly 2>&1 \
		| tee /tmp/_t1.log; rc=$${PIPESTATUS[0]}; \
	echo DOTS_PASSED=$$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$$' /tmp/_t1.log \
		| tr -cd . | wc -c); \
	exit $$rc

# The chaos pack (tests/test_chaos.py + FaultPolicy units): deterministic
# fault injection — proxy stall/drop/garble, producer SIGKILL, supervised
# restart-and-resync.  Includes the `slow` soak cycles that tier-1 skips.
# See docs/fault_tolerance.md.
# BJX_POSTMORTEM_DIR: every supervised producer/shard death during the
# chaos run dumps a flight-recorder postmortem JSON there (naming the
# quarantined target and the fault events around it) — the chaos
# failure is diagnosable from artifacts, not just exit codes.  See
# docs/observability.md.
chaos:
	env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
		BJX_POSTMORTEM_DIR=obs_artifacts \
		$(PYTHON) -m pytest tests/ -m chaos -q -rs

# The replay-service shard chaos pack (tests/test_replay_service.py):
# SIGKILL a shard process mid-training -> degraded sampling with strata
# renormalized over live shards -> supervised respawn -> checkpoint +
# .btr spill-tail restore -> re-admission with the draw stream
# continuing bit-identically.  Subset of `make chaos` (same marker),
# runnable alone for storage-tier work.  See docs/replay.md.
chaos-replay:
	env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
		BJX_POSTMORTEM_DIR=obs_artifacts \
		$(PYTHON) -m pytest tests/test_replay_service.py -m chaos -q -rs

# The learner-failover chaos pack (tests/test_ha.py): SIGKILL the
# supervised learner process mid-training (live fake-Blender fleet +
# sharded replay + a subscribed serve replica) -> watchdog respawn ->
# resume from the latest complete manifest with the replay draw
# authority reconciled to the cut, weight-bus versions strictly
# monotonic across the respawn, and zero serve-client errors.  Includes
# the `slow`-marked full acceptance that tier-1 skips.  See
# docs/fault_tolerance.md "Learner failover".
chaos-learner:
	env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
		BJX_POSTMORTEM_DIR=obs_artifacts \
		$(PYTHON) -m pytest tests/test_ha.py -m chaos -q -rs

# The autoscale chaos pack (tests/test_autoscale.py): the three SIGKILL
# drills every live resize must survive — a serve replica killed
# MID-DRAIN (watchdog respawn, drain flag survives quarantine, the
# scale-down still completes), the controller killed MID-DECISION (a
# fresh controller adopts the observed in-flight drain instead of
# double-acting), and the NEW replay shard killed MID-HANDOFF (the
# handoff aborts whole, the ownership map untouched, the source keeps
# serving).  Subset of `make chaos` (same marker).  See
# docs/autoscaling.md.
chaos-autoscale:
	env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
		BJX_POSTMORTEM_DIR=obs_artifacts \
		$(PYTHON) -m pytest tests/test_autoscale.py -m chaos -q -rs

# The MPMD pipeline chaos pack (tests/test_mpmd.py): SIGKILL one stage
# process mid-training -> FleetWatchdog respawn -> the stage restores
# its params from the per-stage checkpoint cut, the driver reconciles
# every stage to the lowest applied update and replays the in-flight
# one — same-mid resends deduped by the reply cache, so no microbatch
# is lost or applied twice and the final params match an uninterrupted
# run exactly.  Subset of `make chaos` (same marker).  See
# docs/pipeline.md.
chaos-pipeline:
	env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
		BJX_POSTMORTEM_DIR=obs_artifacts \
		$(PYTHON) -m pytest tests/test_mpmd.py -m chaos -q -rs

# Real-Blender acceptance subset (camera goldens, producer streaming,
# cartpole physics).  Skips cleanly when no Blender is discoverable.
# On a headless host (e.g. a TPU-VM) route Blender through the virtual
# display wrapper so Eevee gets a GL context:
#   make blender-tests BLENDER_WRAPPER=1
blender-tests:
ifdef BLENDER_WRAPPER
	BLENDJAX_BLENDER=$(CURDIR)/scripts/blender_headless.sh \
		$(PYTHON) -m pytest tests/ -m blender -q -rs
else
	$(PYTHON) -m pytest tests/ -m blender -q -rs
endif

# Real-TPU acceptance pack (tests/test_tpu_acceptance.py): fence
# validity, compiled flash <= full attention, routed top-k <= dense
# mixture, wire canary — the owed on-chip confirmations as one command.
# Skips cleanly off-TPU.
tpu-tests:
	# BLENDJAX_REAL_TPU=1 disables conftest's CPU forcing so the pack
	# can reach the hardware
	BLENDJAX_REAL_TPU=1 $(PYTHON) -m pytest tests/ -m tpu -q -rs

bench:
	$(PYTHON) bench.py

# Jax-free RL stepping microbench: lock-step vs async pipelined EnvPool
# (fake-Blender fleet speaking the real wire protocol, 250 us/frame
# physics stand-in).  One JSON line with rl_pipelined_x — the
# serialization tax recovered by step_async/step_wait.  See
# docs/rl_stepping.md.
rlbench:
	env -u PALLAS_AXON_POOL_IPS $(PYTHON) benchmarks/rl_benchmark.py \
		--instances 4 --seconds 15 --physics-us 250 \
		--compare --pipeline-depth 4

# Sebulba sharded actor-learner microbench (docs/sharded_rl.md): 4
# env fleets feeding a learner sharded over 8 fake CPU devices vs the
# single-fleet/single-device configuration, interleaved window pairs,
# median ratio as rl_sharded_x (floor 1.5).  The 8 ms physics stand-in
# puts the fleet in the simulation-bound regime the sharded split is
# for (a realistic Blender scene tick; the near-zero-physics protocol
# tax is rlbench's subject) — on a 2-core CI box lighter physics
# saturates the cores with producer work and measures oversubscription
# instead of the architecture.
rlbench-sharded:
	env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
		$(PYTHON) benchmarks/rl_benchmark.py \
		--sharded --mesh-devices 8 --fleets 4 --instances 4 \
		--seconds 24 --physics-us 8000

# The sharding/multihost tier on the 8-fake-device MULTICHIP harness —
# the reproducible local entry point behind the MULTICHIP_r0x.json
# artifacts (before this target only `dryrun` set the virtual-device
# flag).  Runs the mesh/sharding/multihost/sharded-RL test files, then
# the __graft_entry__ multi-parallelism dry run.
multichip:
	env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
		XLA_FLAGS="--xla_force_host_platform_device_count=8" \
		$(PYTHON) -m pytest tests/test_sharding.py tests/test_multihost.py \
		tests/test_actor_learner_sharded.py tests/test_prefetch.py \
		tests/test_pipeline.py -q -rs
	env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
		XLA_FLAGS="--xla_force_host_platform_device_count=8" \
		$(PYTHON) __graft_entry__.py

# Jax-free replay-path microbench: appends/sec into the columnar ring,
# batched columnar vs naive per-item sampling (replay_sample_x, floor
# 2.0 at batch 32), the FileRecorder buffered-vs-unbuffered write
# comparison, and (--sharded) the replay-service windows — in-process
# vs ShardedReplay over 2 in-process shard servers in interleaved
# windows (replay_shard_x = the storage tier's wire tax) plus the
# degraded-mode sampling overhead with one shard quarantined
# (replay_degraded_x).  One JSON line; see docs/replay.md.
replaybench:
	env -u PALLAS_AXON_POOL_IPS $(PYTHON) benchmarks/replay_benchmark.py \
		--batch 32 --seconds 6 --sharded

# ShmRPC transport microbench (docs/transport.md): the replay-service
# windows with BOTH wires interleaved over the same shard servers —
# replay_shard_x from the shm arm (the storage tier's wire tax after
# the shared-memory transport) and shm_rpc_x (shm over loopback ZMQ at
# the median pair; floor trajectory-guarded in bench_compare).  Longer
# windows than replaybench: this is the transport's dedicated entry
# point.
shmbench:
	env -u PALLAS_AXON_POOL_IPS $(PYTHON) benchmarks/replay_benchmark.py \
		--batch 32 --seconds 10 --sharded --transport shm

# Policy-serving microbench (docs/serving.md): 8 concurrent episode
# clients against one continuously-batched seqformer world-model
# server (KV-cache slot pool, per-row positions) vs the serial
# one-request-per-REP baseline vs the int8-quantized server, in
# interleaved order-rotated rounds.  One JSON line with the serving
# headline: serve_qps, serve_p99_ms (client-observed union p99),
# serve_batch_x (floor > 1 at 8 clients), serve_int8_x.
servebench:
	env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
		$(PYTHON) benchmarks/serve_benchmark.py \
		--seconds 18 --clients 8

# Serve-fleet scale-out microbench (docs/serving.md "ServeGateway"): 3
# linear-model replica processes (sleep-based --work-us per-row compute
# stand-in, so replica compute is what scales) behind one ServeGateway,
# 16 clients, interleaved 1-replica (others DRAINED) vs 3-replica
# windows.  One JSON line with gateway_qps, gateway_p99_ms
# (client-observed union p99) and gateway_scale_x (aggregate QPS at 3
# replicas over 1 at the median pair; ~2.2 on the 2-core CI box — the
# gap to 3.0 is the box's 2 cores carrying 16 GIL-bound bench clients
# plus the single-threaded gateway hop).
gatewaybench:
	env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
		$(PYTHON) benchmarks/serve_benchmark.py \
		--gateway --replicas 3 --gateway-workers 2 \
		--client-procs 4 --seconds 27 --clients 16

# WeightBus live-rollout microbench (docs/weight_bus.md): 6 concurrent
# episode clients against one subscribed linear-model server while an
# in-process publisher pushes a fresh 256 KiB versioned snapshot every
# ~800 ms.  One JSON line with weight_swap_ms (publish -> first
# client-observed reply at the new version, p99 over the window's
# swaps; ceiling-guarded in bench_compare) and weight_swap_qps_dip_x
# (QPS through the swap over steady state; floor 0.80).  Jax-free.
weightbench:
	env -u PALLAS_AXON_POOL_IPS $(PYTHON) benchmarks/weight_benchmark.py \
		--seconds 10 --clients 6

# Scenario-plane microbench (docs/scenarios.md): a 2-scenario
# fake-Blender fleet at very different physics rates (lite 200 us vs
# rich 4 ms), lock-step homogeneous batching vs ready-first
# step_wait(min_ready=1) over the SAME fleet in interleaved window
# pairs -> scenario_hetero_x (the throughput the slow scenario no
# longer steals); then the batched serve tier under a weighted
# labelled traffic mix -> serve_mix_p99_ms (the union tail a realistic
# multi-scenario workload observes).  Jax-free; both numbers carried
# in the bench headline with bench_compare bounds.
scenariobench:
	env -u PALLAS_AXON_POOL_IPS $(PYTHON) benchmarks/scenario_benchmark.py \
		--seconds 20 --instances 2 --clients 6

# Learner-failover microbench (docs/fault_tolerance.md "Learner
# failover"): ckpt_overhead_x (off-policy update throughput with the
# async TrainCheckpointer on vs off, interleaved window pairs — target
# ~1.0, floor 0.90) and learner_recovery_s (SIGKILL of the supervised
# learner process on a live fake-Blender fleet -> first completed
# post-respawn update, watchdog + respawn + jax import + manifest
# restore + first jitted update included).  One JSON line, both carried
# in the bench.py headline with bench_compare bounds.
habench:
	env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
		$(PYTHON) benchmarks/ha_benchmark.py

# Autoscale microbench (docs/autoscaling.md): resize_settle_s (the
# controller's scale-up decision -> fleet verified healthy at the new
# size under steady client traffic, healthy window included — lower is
# better, bench_compare ceiling) and drain_error_x (client-observed
# error fraction across the drain -> verify -> retire scale-down —
# MUST be 0.0).  One JSON line, both carried in the bench.py headline.
autoscalebench:
	env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
		$(PYTHON) benchmarks/autoscale_benchmark.py

# MPMD pipeline microbench (docs/pipeline.md): N-stage stage-process
# pipeline vs a 1-stage same-harness baseline in interleaved windows;
# the `pipe_mpmd_x` throughput ratio is carried into the bench headline
# (bench_compare floors it).
pipebench:
	env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
		$(PYTHON) benchmarks/pipeline_benchmark.py

# Bench-trajectory guardrail (docs/observability.md): diff two bench
# artifacts with per-metric regression floors; non-zero exit on any
# metric below its floor.  Accepts raw bench.py stdout, headline lines,
# and the driver capture wrappers (BENCH_r0x.json).
#   make benchdiff OLD=BENCH_r05.json NEW=BENCH_new.json
OLD ?= BENCH_r05.json
NEW ?= BENCH_new.json
benchdiff:
	$(PYTHON) scripts/bench_compare.py $(OLD) $(NEW)

# Telemetry-plane demo (docs/observability.md): a short fake-Blender
# pipeline with tracing on, emitting into obs_artifacts/ —
#   trace.perfetto.json  one merged Chrome/Perfetto timeline with
#                        producer- and consumer-side spans of the same
#                        correlation ids across >= 3 pids,
#   scrape.json/.prom    a TelemetryHub scrape (zero-filled canonical
#                        counters+stages, latency percentiles) in both
#                        exposition formats, pulled over the ZMQ REP
#                        scrape socket,
#   postmortem-*.json    a forced flight-recorder dump naming a
#                        quarantined target.
obsdemo:
	env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
		$(PYTHON) scripts/obs_demo.py --out obs_artifacts

dryrun:
	env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
		XLA_FLAGS="--xla_force_host_platform_device_count=8" \
		$(PYTHON) __graft_entry__.py
