"""Driver benchmark: two JSON lines on stdout, guaranteed — the full
artifact first, then a compact headline summary LAST so a bounded tail
capture of stdout always carries the verdict (the r04 driver artifact
lost its own metric/value to tail truncation of the single big line).
Both lines are valid driver lines (metric/value/unit/vs_baseline
present); consumers wanting the full evidence should take the FIRST
line, tail-limited consumers get the headline.

Orchestrates ``benchmarks/suite.py`` (a child process that measures the
end-to-end pipeline in progressive phases, emitting a JSON line per phase
the moment it completes) plus ``benchmarks/rl_benchmark.py`` (the
reference's second headline number), and assembles the driver's single
JSON line from whatever arrived.

Honest labeling (the reference's 0.012 s/image *includes* Blender
rendering; ours cannot — Blender does not run in this image — so the
streamed pixels come from synthetic producers speaking the real wire
protocol):

- ``includes_rendering``: always false here; ``vs_baseline`` therefore
  compares transport+train throughput against the reference's
  full-pipeline number and must be read with that asterisk.
- both configurations are reported side by side: ``stream_to_hbm`` (feed
  only) and ``stream_to_train`` (feed + detector step), plus the
  MXU-bound ``seqformer`` phase with train duty cycle and MFU — the
  BASELINE.md north-star measurements.

Robustness: the child emits per-phase lines immediately, so a deadline
kill still yields every completed phase (round 1 lost its TPU numbers to
an all-or-nothing child timeout).  The JAX persistent compilation cache
(``.jax_cache/``) absorbs first-compile cost across runs.  If no phase
arrives at all, a host-only measurement (recv + collate, no jax) is taken
in-process — the driver always gets its line.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import threading
import time

HERE = os.path.dirname(os.path.abspath(__file__))
# driver kills around 540+; leave slack for fallback ($BJX_BENCH_BUDGET
# overrides for quick local runs)
TOTAL_BUDGET_S = float(os.environ.get("BJX_BENCH_BUDGET", 520))
RL_BUDGET_S = 90
REF_SEC_PER_IMAGE = 0.012  # reference 4-instance number, rendering included


def host_only_fallback(seconds=10.0):
    """Measure the host half of the pipeline (no jax): producers -> fan-in
    recv -> collate."""
    from benchmarks.benchmark import launch_producers

    from blendjax.btt.dataset import RemoteIterableDataset
    from blendjax.btt.loader import BatchLoader

    cores = os.cpu_count() or 1
    n_prod = 4 if cores >= 4 else 1
    addrs, procs = launch_producers(n_prod, raw=True, width=640, height=480)
    try:
        ds = RemoteIterableDataset(addrs, max_items=10**9, timeoutms=60000)
        with BatchLoader(ds, batch_size=8, num_workers=min(4, cores)) as loader:
            it = iter(loader)
            for _ in range(8):
                next(it)  # warmup
            t0 = time.perf_counter()
            n = 0
            while time.perf_counter() - t0 < seconds:
                next(it)
                n += 1
            dt = time.perf_counter() - t0
        return (n * 8) / dt
    finally:
        for p in procs:
            p.terminate()
        for p in procs:
            try:
                p.wait(timeout=5)
            except subprocess.TimeoutExpired:
                p.kill()


def run_child_collect_json(cmd, env, deadline_s):
    """Run a child, reading stdout live; return parsed JSON lines.

    On deadline the child's process group is killed — lines already
    received are kept (the whole point of progressive emission)."""
    lines = []
    proc = subprocess.Popen(
        cmd,
        stdout=subprocess.PIPE,
        stderr=None,  # inherit: suite diagnostics must reach driver logs
        text=True,
        cwd=HERE,
        env=env,
        start_new_session=True,
    )

    def reader():
        for line in proc.stdout:
            line = line.strip()
            if line.startswith("{"):
                try:
                    lines.append(json.loads(line))
                except json.JSONDecodeError:
                    pass

    t = threading.Thread(target=reader, daemon=True)
    t.start()
    try:
        proc.wait(timeout=deadline_s)
    except subprocess.TimeoutExpired:
        sys.stderr.write(f"child {cmd[1]} hit {deadline_s:.0f}s deadline\n")
        # TERM first: suite.py's handler kills its device-child sessions
        # (they are NOT in our child's process group) and sweeps its rings
        try:
            os.killpg(proc.pid, signal.SIGTERM)
        except OSError:
            proc.terminate()
        try:
            proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            try:
                os.killpg(proc.pid, signal.SIGKILL)
            except OSError:
                proc.kill()
            proc.wait(timeout=10)
        _sweep_shm(proc.pid)  # killed producers never unlink their rings
    t.join(timeout=5)
    return lines


def _sweep_shm(child_pid):
    """Remove shm rings leaked by THIS run's SIGKILLed suite child (the
    producers' unlink path never runs under killpg); names embed the suite
    child's pid, so the sweep can't touch a concurrently running suite."""
    import glob

    for path in glob.glob(f"/dev/shm/bjx-suite-*-{child_pid}-*"):
        try:
            os.unlink(path)
        except OSError:
            pass


def probe_log_summary(path=None):
    """Summarize the round-5 tunnel liveness probe log for the artifact.

    When the driver run lands on the CPU fallback, the artifact itself
    carries the documented record of every attempt to reach the TPU
    (VERDICT r4 next #1: 'if the tunnel never returns, document the
    attempt') — attempts, successes, and the last status, straight from
    ``benchmarks/tunnel_probe.sh``'s append-only log."""
    path = path or os.path.join(
        HERE, "benchmarks", "results", "r05_tunnel_probes.jsonl"
    )
    try:
        with open(path) as fp:
            lines = [ln for ln in fp if ln.strip()]
    except OSError:
        return None
    rows = []
    for ln in lines:
        # the probe loop appends concurrently: skip torn/garbage lines
        # instead of discarding the whole record (or crashing the run)
        try:
            r = json.loads(ln)
        except json.JSONDecodeError:
            continue
        if isinstance(r, dict):
            rows.append(r)
    probes = [r for r in rows if "alive" in r]
    if not probes:
        return None
    # a cpu-platform "alive" means the probe child fell back to the CPU
    # backend — the tunnel was NOT reached (tunnel_probe.sh draws the
    # same line for TUNNEL_UP)
    alive = [r for r in probes
             if r["alive"] and r.get("platform") != "cpu"]
    out = {
        "attempts": len(probes),
        "alive_count": len(alive),
        "first_ts": probes[0].get("ts"),
        "last_ts": probes[-1].get("ts"),
        "last_alive": probes[-1]["alive"],
    }
    if alive:
        out["last_alive_ts"] = alive[-1].get("ts")
    return out


def feed_bound_phase(seconds=3.0):
    """Measure the feed ceiling (batch assembly with a trivial train
    step), legacy collate vs arena-pooled scatter — jax-free, in-process,
    so the number lands even when the accelerator (or its tunnel) is
    down.  See benchmarks/feed_bound.py."""
    from benchmarks.feed_bound import measure

    return measure(seconds=seconds)


def replay_bench_phase(seconds=5.0):
    """Measure the replay subsystem (benchmarks/replay_benchmark.py):
    ring append rate, batched columnar vs naive per-item sampling
    (``replay_sample_x``), the FileRecorder buffered-write win, AND the
    sharded replay-service comparison (in-process vs service windows ->
    ``replay_shard_x``, plus the degraded-mode sampling overhead with a
    shard quarantined -> ``replay_degraded_x``) — jax-free, in-process,
    same rationale as the feed-bound phase."""
    from benchmarks.replay_benchmark import measure

    return measure(seconds=seconds, sharded=2)


def main():
    sys.path.insert(0, HERE)
    try:
        from blendjax.native import native_available

        native = native_available()
    except Exception:
        native = False
    from blendjax.btt.launcher import child_env

    env = child_env()
    # persistent compile cache: first round pays the compiles, every later
    # run (and re-run within a round) hits the cache
    env.setdefault("JAX_COMPILATION_CACHE_DIR", os.path.join(HERE, ".jax_cache"))
    env.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0")

    t_start = time.monotonic()
    # feed-bound mode first: cheap (~20 s), jax-free, and measures the
    # assembly ceiling the wire-efficiency story needs (BENCH_r05 flagged
    # wire_efficiency_meaningful: false because no mode observed the feed)
    feed_bound = None
    try:
        feed_bound = feed_bound_phase()
    except Exception as e:  # noqa: BLE001 - the suite phases still run
        sys.stderr.write(f"feed_bound phase failed: {type(e).__name__}: {e}\n")
    # replay-path ceiling rides along under the same jax-free budget: the
    # off-policy workload's sampling rate (and its columnar speedup) is a
    # first-class headline next to the feed's
    replay_bench = None
    try:
        replay_bench = replay_bench_phase()
    except Exception as e:  # noqa: BLE001 - the suite phases still run
        sys.stderr.write(
            f"replay_bench phase failed: {type(e).__name__}: {e}\n"
        )
    cores = os.cpu_count() or 1
    instances = 4 if cores >= 4 else 1
    workers = 4 if cores >= 4 else 1
    suite_budget = max(60.0, TOTAL_BUDGET_S - RL_BUDGET_S - 30)
    cmd = [
        sys.executable,
        os.path.join(HERE, "benchmarks", "suite.py"),
        "--budget", str(suite_budget),
        "--instances", str(instances),
        "--workers", str(workers),
        "--batch", "8",
        "--prefetch", "12",
    ]
    cmd += ["--raw", "--transport", "shm"] if native else ["--pickle"]
    phases = {
        p.get("phase"): p
        for p in run_child_collect_json(cmd, env, suite_budget + 30)
    }

    rl = None
    rl_physics = None
    # the RL children never touch the accelerator (podracer pins jax to
    # cpu; the RPC configuration is jax-free) — strip the axon trigger so
    # a dead tunnel relay can't hang them at import (see suite.py)
    rl_env = dict(env)
    rl_env.pop("PALLAS_AXON_POOL_IPS", None)
    rl_env["JAX_PLATFORMS"] = "cpu"
    remaining = TOTAL_BUDGET_S - (time.monotonic() - t_start) - 20
    if remaining > 30:
        rl_lines = run_child_collect_json(
            [
                sys.executable,
                os.path.join(HERE, "benchmarks", "rl_benchmark.py"),
                "--instances", str(instances),
                "--seconds", "8",
            ],
            rl_env,
            min(RL_BUDGET_S, remaining),
        )
        rl = rl_lines[-1] if rl_lines else None
    # second configuration: 250 us busy-wait per step stands in for a
    # physics solver tick (the reference's ~2000 Hz cartpole spends
    # <500 us/step on everything incl. RPC), so the RL claim also has a
    # with-physics-cost number
    remaining = TOTAL_BUDGET_S - (time.monotonic() - t_start) - 20
    if rl and remaining > 25:
        rl_lines = run_child_collect_json(
            [
                sys.executable,
                os.path.join(HERE, "benchmarks", "rl_benchmark.py"),
                "--instances", str(instances),
                "--seconds", "5",
                "--physics-us", "250",
            ],
            rl_env,
            min(45, remaining),
        )
        rl_physics = rl_lines[-1] if rl_lines else None
    # third configuration: the async pipelined path at the same 250 us
    # physics cost — the with-physics serialization tax is exactly what
    # step_async/step_wait hides.  --compare interleaves lock-step and
    # pipelined windows on ONE fleet and reports the median paired ratio
    # (rl_pipelined_x), which survives the 2x throughput drift of shared
    # CI hosts that back-to-back whole runs do not
    rl_pipelined = None
    remaining = TOTAL_BUDGET_S - (time.monotonic() - t_start) - 20
    if rl_physics and remaining > 45:
        rl_lines = run_child_collect_json(
            [
                sys.executable,
                os.path.join(HERE, "benchmarks", "rl_benchmark.py"),
                "--instances", str(instances),
                "--seconds", "15",
                "--physics-us", "250",
                "--compare", "--pipeline-depth", "4",
            ],
            rl_env,
            min(75, remaining),
        )
        rl_pipelined = rl_lines[-1] if rl_lines else None
    # fourth configuration: the Sebulba sharded actor-learner on the
    # 8-fake-device MULTICHIP harness (4 fleets feeding a P('data')-
    # sharded learner vs the single-fleet/single-device path) —
    # interleaved window pairs, median ratio rl_sharded_x.  8 ms physics
    # puts the fleet in the simulation-bound regime the sharded split
    # scales (see make rlbench-sharded); the child forces its own
    # virtual-device count before importing jax
    rl_sharded = None
    remaining = TOTAL_BUDGET_S - (time.monotonic() - t_start) - 20
    if remaining > 75:
        rl_lines = run_child_collect_json(
            [
                sys.executable,
                os.path.join(HERE, "benchmarks", "rl_benchmark.py"),
                "--instances", str(instances),
                "--seconds", "24",
                "--physics-us", "8000",
                "--sharded", "--mesh-devices", "8", "--fleets", "4",
            ],
            rl_env,
            min(120, remaining),
        )
        rl_sharded = rl_lines[-1] if rl_lines else None
    # fifth configuration: the policy-serving inference tier
    # (docs/serving.md) — 8 concurrent episode clients against one
    # continuously-batched seqformer world-model server, interleaved
    # against the serial one-request-per-REP baseline and the int8
    # server: serve_qps + serve_p99_ms headline, serve_batch_x /
    # serve_int8_x ratios.  CPU-pinned child (jax, loopback wire).
    serve_bench = None
    remaining = TOTAL_BUDGET_S - (time.monotonic() - t_start) - 20
    if remaining > 45:
        serve_lines = run_child_collect_json(
            [
                sys.executable,
                os.path.join(HERE, "benchmarks", "serve_benchmark.py"),
                "--seconds", "18",
                "--clients", "8",
            ],
            rl_env,
            min(90, remaining),
        )
        serve_bench = serve_lines[-1] if serve_lines else None
    # sixth configuration: the serve FLEET (docs/serving.md
    # "ServeGateway" + "The sharded gateway") — 3 replica processes
    # behind the SHARDED gateway (2 worker processes + front), with
    # interleaved 1-replica (drained) vs 3-replica windows
    # (gateway_scale_x, replica scale-out, replica-bound fleet) AND a
    # second phase of 1-worker (single-address relay) vs 2-worker
    # (partitioned direct dial) windows over its own gateway-bound
    # fleet (gateway_shard_x); bench clients ride their own processes
    # (--client-procs) so their GIL never throttles the data plane.
    # gateway_qps + gateway_p99_ms headline.  Jax-free (linear
    # replicas).
    gateway_bench = None
    remaining = TOTAL_BUDGET_S - (time.monotonic() - t_start) - 20
    if remaining > 40:
        gw_lines = run_child_collect_json(
            [
                sys.executable,
                os.path.join(HERE, "benchmarks", "serve_benchmark.py"),
                "--gateway", "--replicas", "3",
                "--gateway-workers", "2",
                "--client-procs", "4",
                "--seconds", "27",
                "--clients", "16",
            ],
            rl_env,
            min(150, remaining),
        )
        gateway_bench = gw_lines[-1] if gw_lines else None

    # seventh configuration: the WeightBus live-rollout cost
    # (docs/weight_bus.md) — a subscribed linear-model server under
    # live traffic while versioned snapshots publish and hot-swap:
    # weight_swap_ms (publish -> first serving reply at the new
    # version, p99) and weight_swap_qps_dip_x (QPS through the swap
    # over steady state).  Jax-free.
    weight_bench = None
    remaining = TOTAL_BUDGET_S - (time.monotonic() - t_start) - 20
    if remaining > 25:
        wb_lines = run_child_collect_json(
            [
                sys.executable,
                os.path.join(HERE, "benchmarks", "weight_benchmark.py"),
                "--seconds", "10",
                "--clients", "6",
            ],
            rl_env,
            min(60, remaining),
        )
        weight_bench = wb_lines[-1] if wb_lines else None

    # eighth configuration: the scenario plane (docs/scenarios.md) —
    # a 2-scenario heterogeneous fleet stepped ready-first vs the
    # lock-step homogeneous batch path (scenario_hetero_x), plus the
    # batched serve tier under a labelled multi-scenario traffic mix
    # (serve_mix_p99_ms).  Jax-free.
    scenario_bench = None
    remaining = TOTAL_BUDGET_S - (time.monotonic() - t_start) - 20
    if remaining > 30:
        sc_lines = run_child_collect_json(
            [
                sys.executable,
                os.path.join(HERE, "benchmarks", "scenario_benchmark.py"),
                "--seconds", "18",
                "--instances", "2",
                "--clients", "6",
            ],
            rl_env,
            min(75, remaining),
        )
        scenario_bench = sc_lines[-1] if sc_lines else None

    # ninth configuration: the learner-failover plane
    # (docs/fault_tolerance.md "Learner failover") — ckpt_overhead_x
    # (async TrainCheckpointer on vs off over interleaved run_offline
    # windows) and learner_recovery_s (supervised learner SIGKILL ->
    # first post-respawn completed update).
    ha_bench = None
    remaining = TOTAL_BUDGET_S - (time.monotonic() - t_start) - 20
    if remaining > 40:
        ha_lines = run_child_collect_json(
            [
                sys.executable,
                os.path.join(HERE, "benchmarks", "ha_benchmark.py"),
            ],
            rl_env,
            min(150, remaining),
        )
        ha_bench = ha_lines[-1] if ha_lines else None

    # tenth configuration: the autoscale plane (docs/autoscaling.md) —
    # resize_settle_s (scale-up decision -> fleet verified healthy at
    # the new size under steady traffic) and drain_error_x (client-
    # observed error fraction across a drain scale-down — must be 0).
    # Jax-free.
    autoscale_bench = None
    remaining = TOTAL_BUDGET_S - (time.monotonic() - t_start) - 20
    if remaining > 30:
        as_lines = run_child_collect_json(
            [
                sys.executable,
                os.path.join(HERE, "benchmarks", "autoscale_benchmark.py"),
            ],
            rl_env,
            min(90, remaining),
        )
        autoscale_bench = as_lines[-1] if as_lines else None

    # eleventh configuration: the MPMD pipeline-parallel learner
    # (docs/pipeline.md) — N stage processes with 1F1B microbatch
    # interleaving vs a 1-stage same-harness baseline, interleaved
    # windows, calibrated per-stage compute stand-in.
    pipeline_bench = None
    remaining = TOTAL_BUDGET_S - (time.monotonic() - t_start) - 20
    if remaining > 40:
        pb_lines = run_child_collect_json(
            [
                sys.executable,
                os.path.join(HERE, "benchmarks", "pipeline_benchmark.py"),
            ],
            rl_env,
            min(150, remaining),
        )
        pipeline_bench = pb_lines[-1] if pb_lines else None

    out = assemble(phases, rl, rl_physics, host_fallback=host_only_fallback,
                   feed_bound=feed_bound, rl_pipelined=rl_pipelined,
                   replay_bench=replay_bench, rl_sharded=rl_sharded,
                   serve_bench=serve_bench, gateway_bench=gateway_bench,
                   weight_bench=weight_bench,
                   scenario_bench=scenario_bench, ha_bench=ha_bench,
                   autoscale_bench=autoscale_bench,
                   pipeline_bench=pipeline_bench)
    if out.get("device") != "tpu":
        probes = probe_log_summary()
        if probes:
            out["tunnel_probe_log"] = probes
    print(json.dumps(out), flush=True)
    # The full line can exceed a tail-capture window (the r04 driver
    # artifact lost its own metric/value to truncation — VERDICT r4 weak
    # #1).  Emit a compact summary LAST so the trailing bytes of stdout
    # always carry the verdict; it is itself a valid driver line
    # (metric/value/unit/vs_baseline present).
    print(json.dumps(headline(out)), flush=True)


#: keys the compact trailing line carries verbatim (driver-line fields
#: spelled out so the summary is itself a valid driver line), plus the
#: abbreviated evidence keys below; chosen so the last 400 bytes of
#: stdout always answer: what was measured, on what device, against what
#: wire ceiling, with valid fences or not
HEADLINE_KEYS = (
    "metric", "value", "unit", "vs_baseline", "vs_baseline_comparable",
    "train_degraded", "wire_bound", "device", "duty_cycle_invalid",
)
#: full-artifact key -> compact headline key (byte budget: the whole
#: line must fit a 400-byte tail capture; test_bench_assembly locks it)
HEADLINE_ABBREV = (
    ("wire_limit_images_per_sec", "wire_limit"),
    ("pipeline_wire_efficiency", "wire_eff"),
    ("wire_efficiency_meaningful", "wire_eff_ok"),
    ("train_duty_cycle", "duty"),
)
#: the headline byte ceiling (newline included) and the key GROUPS
#: dropped — in order, only while over the ceiling — to get under it.
#: 'attn' goes first: whenever the line is long enough to overflow (the
#: banked partial-record shapes), flash_over_full is present and
#: already witnesses that the flash kernel ran.  A value and the
#: honesty flag qualifying it are dropped TOGETHER (never the flag
#: alone — a tail reader must not see a number whose 'untrustworthy'
#: marker was trimmed).  Everything here is recoverable from the full
#: artifact line; driver fields, the kernel verdict ratios, and the
#: partial/degraded markers are never dropped.
HEADLINE_BYTE_BUDGET = 400
HEADLINE_TRIM_ORDER = (
    ("telemetry_overhead_x",),
    ("pipe_mpmd_x",),
    ("resize_settle_s", "drain_error_x"),
    ("ckpt_overhead_x", "learner_recovery_s"),
    ("scenario_hetero_x", "serve_mix_p99_ms"),
    ("weight_swap_ms", "weight_swap_qps_dip_x"),
    ("serve_int8_x",),
    ("serve_prefill_x",),
    ("shm_rpc_x",),
    ("replay_shard_x", "replay_degraded_x"),
    ("serve_batch_x",),
    ("gateway_qps", "gateway_p99_ms"),
    ("rl_sharded_x",),
    ("replay_sample_x",),
    ("gateway_scale_x", "gateway_shard_x"),
    ("serve_qps", "serve_p99_ms"),
    ("feed_arena_x",),
    ("rl_pipelined_x",),
    ("attn",),
    ("wire_limit", "wire_eff", "wire_eff_ok"),
    ("duty", "duty_cycle_invalid", "seq_duty", "seq_duty_invalid"),
)


def headline(out):
    """Compact summary of an assembled artifact (printed after it)."""
    line = {"headline": True}
    for k in HEADLINE_KEYS:
        if k in out:
            line[k] = out[k]
    for k, short in HEADLINE_ABBREV:
        if k in out:
            line[short] = out[k]
    fb = out.get("feed_bound")
    if fb and fb.get("arena_over_legacy") is not None:
        # arena assembly speedup over legacy collate at the feed ceiling
        line["feed_arena_x"] = fb["arena_over_legacy"]
    if fb and fb.get("telemetry_overhead_x") is not None:
        # telemetry-plane sanity: feed throughput with hub+histograms
        # enabled over disabled (floor 0.95 — see docs/observability.md)
        line["telemetry_overhead_x"] = fb["telemetry_overhead_x"]
    rb = out.get("replay_bench")
    if rb and rb.get("replay_sample_x") is not None:
        # columnar batched replay sampling speedup over naive per-item
        # collation (batch 32) — the off-policy workload's feed ceiling
        line["replay_sample_x"] = rb["replay_sample_x"]
    shard = (rb or {}).get("sharded")
    if shard and shard.get("replay_shard_x") is not None:
        # replay-service sampling rate over in-process (the wire tax of
        # the sharded storage tier — the service arm rides ShmRPC by
        # default since ISSUE-12), with the degraded-mode overhead
        # (one shard quarantined, strata renormalized) alongside
        line["replay_shard_x"] = shard["replay_shard_x"]
        if shard.get("shm_rpc_x") is not None:
            # the shared-memory transport over loopback ZMQ at the
            # median interleaved pair (docs/transport.md)
            line["shm_rpc_x"] = shard["shm_rpc_x"]
        if shard.get("replay_degraded_x") is not None:
            line["replay_degraded_x"] = shard["replay_degraded_x"]
    if out.get("rl_pipelined_x") is not None:
        # async pipelined EnvPool speedup over lock-step at physics 250us
        line["rl_pipelined_x"] = out["rl_pipelined_x"]
    if out.get("rl_sharded_x") is not None:
        # Sebulba sharded actor-learner speedup over single-device at
        # 4 fleets / 8 fake devices (simulation-bound, physics 8 ms)
        line["rl_sharded_x"] = out["rl_sharded_x"]
    sb = out.get("serve_bench")
    if sb and sb.get("serve_qps") is not None:
        # the policy-serving tier headline: batched QPS + client-
        # observed p99 at 8 concurrent episodes, with the continuous-
        # batching-over-serial-REP and int8-over-float ratios
        line["serve_qps"] = sb["serve_qps"]
        if sb.get("serve_p99_ms") is not None:
            line["serve_p99_ms"] = sb["serve_p99_ms"]
        if sb.get("serve_batch_x") is not None:
            line["serve_batch_x"] = sb["serve_batch_x"]
        if sb.get("serve_int8_x") is not None:
            line["serve_int8_x"] = sb["serve_int8_x"]
        if sb.get("serve_prefill_x") is not None:
            # batched prefill admission over T serial decode steps
            line["serve_prefill_x"] = sb["serve_prefill_x"]
    gb = out.get("gateway_bench")
    if gb and gb.get("gateway_qps") is not None:
        # the serve-FLEET headline: aggregate QPS through the gateway
        # at 3 replicas, client-observed union p99, and the scale-out
        # ratio vs the same fleet with all but one replica drained
        line["gateway_qps"] = gb["gateway_qps"]
        if gb.get("gateway_p99_ms") is not None:
            line["gateway_p99_ms"] = gb["gateway_p99_ms"]
        if gb.get("gateway_scale_x") is not None:
            line["gateway_scale_x"] = gb["gateway_scale_x"]
        if gb.get("gateway_shard_x") is not None:
            # the sharded data plane's win: N gateway workers over one
            line["gateway_shard_x"] = gb["gateway_shard_x"]
    wb = out.get("weight_bench")
    if wb and wb.get("weight_swap_ms") is not None:
        # the live-rollout headline: publish -> first serving reply at
        # the new version (p99) and the QPS dip through the swap
        line["weight_swap_ms"] = wb["weight_swap_ms"]
        if wb.get("weight_swap_qps_dip_x") is not None:
            line["weight_swap_qps_dip_x"] = wb["weight_swap_qps_dip_x"]
    sc = out.get("scenario_bench")
    if sc and sc.get("scenario_hetero_x") is not None:
        # the scenario-plane headline: heterogeneous-fleet throughput
        # over the lock-step homogeneous batch path, and the serve
        # tier's union p99 under a labelled multi-scenario traffic mix
        line["scenario_hetero_x"] = sc["scenario_hetero_x"]
        if sc.get("serve_mix_p99_ms") is not None:
            line["serve_mix_p99_ms"] = sc["serve_mix_p99_ms"]
    ha = out.get("ha_bench")
    if ha:
        # the learner-failover headline: async-checkpointing overhead
        # (~1.0 = the update loop pays only the bounded barrier) and
        # the SIGKILL -> first-post-respawn-update outage
        if ha.get("ckpt_overhead_x") is not None:
            line["ckpt_overhead_x"] = ha["ckpt_overhead_x"]
        if ha.get("learner_recovery_s") is not None:
            line["learner_recovery_s"] = ha["learner_recovery_s"]
    asb = out.get("autoscale_bench")
    if asb:
        # the autoscale headline: scale-up decision -> verified-healthy
        # settle, and the zero-client-visible-errors drain contract
        if asb.get("resize_settle_s") is not None:
            line["resize_settle_s"] = asb["resize_settle_s"]
        if asb.get("drain_error_x") is not None:
            line["drain_error_x"] = asb["drain_error_x"]
    pb = out.get("pipeline_bench")
    if pb and pb.get("pipe_mpmd_x") is not None:
        # the MPMD pipeline headline: N stage processes' 1F1B schedule
        # over the 1-stage same-harness baseline (floor 1.5 at 3 stages)
        line["pipe_mpmd_x"] = pb["pipe_mpmd_x"]
    fv = out.get("fence_validation")
    if fv:
        ok = fv.get("fence_ok")
        # collapse to the validity of the fence actually used (value
        # fetch); the per-fence detail stays in the full line
        line["fence_ok"] = ok.get("fetch") if isinstance(ok, dict) else ok
    seq = out.get("seqformer")
    if seq:
        if "attn" in seq:
            line["attn"] = seq["attn"]
        if "flash_over_full" in seq:
            line["flash_over_full"] = seq["flash_over_full"]
        if seq.get("stream_pending") or seq.get("window_skipped"):
            # banked confirm-first record survived a mid-stream kill, or
            # the budget expired before the streaming window: the step
            # verdict is real, the stream window never ran
            line["seq_partial"] = True
        if seq.get("train_duty_cycle") is not None:
            line["seq_duty"] = seq["train_duty_cycle"]
            if seq.get("duty_cycle_invalid"):
                line["seq_duty_invalid"] = True
    moe = out.get("moe_compare")
    if moe and "topk_over_dense_mixture" in moe:
        line["topk_over_dense"] = moe["topk_over_dense_mixture"]
        if moe.get("partial"):
            # banked record survived a kill during mlp/topk_alt: the
            # ratio is real, the optional variants never ran
            line["moe_partial"] = True
    # bare-kernel fallbacks: surface only when the stronger train-step
    # ratio is absent (short window banked the micro verdict alone)
    ka = out.get("kernel_attn")
    if ka and "flash_over_full" not in line:
        if "flash_over_full_kernel" in ka:
            line["flash_over_full_kernel"] = ka["flash_over_full_kernel"]
        elif "flash_step_ms" in ka and ka.get("flash_compiled"):
            # flash ran compiled on this device even if the full-attn
            # comparison never landed
            line["flash_kernel_ran"] = True
    km = out.get("kernel_moe")
    if km and "topk_over_dense" not in line \
            and "topk_over_dense_kernel" in km:
        line["topk_over_dense_kernel"] = km["topk_over_dense_kernel"]
    for group in HEADLINE_TRIM_ORDER:
        if len(json.dumps(line)) + 1 <= HEADLINE_BYTE_BUDGET:
            break
        for k in group:
            line.pop(k, None)
    return line


def assemble(phases, rl=None, rl_physics=None, host_fallback=None,
             feed_bound=None, rl_pipelined=None, replay_bench=None,
             rl_sharded=None, serve_bench=None, gateway_bench=None,
             weight_bench=None, scenario_bench=None, ha_bench=None,
             autoscale_bench=None, pipeline_bench=None):
    """Assemble the driver's single JSON object from whatever phase lines
    arrived.  Pure (given ``host_fallback``), so the carry-through of
    stages/windows/canary/fence evidence is unit-testable
    (tests/test_bench_assembly.py)."""
    extras = {"includes_rendering": False}
    if serve_bench and serve_bench.get("phase") == "serve_bench":
        # the inference-tier ceiling: continuous-batched QPS/p99 over
        # the serial baseline + the int8 ratio, stage percentiles
        # included — see benchmarks/serve_benchmark.py
        extras["serve_bench"] = {
            k: serve_bench[k]
            for k in (
                "model", "clients", "slots", "rounds", "window_s",
                "serve_qps", "serve_p50_ms", "serve_p99_ms",
                "serve_batch_x", "serve_int8_x", "serve_prefill_x",
                "prefill", "serve_qps_modes",
                "pair_ratios", "stages",
            )
            if k in serve_bench
        }
    if gateway_bench and gateway_bench.get("phase") == "gateway_bench":
        # the serve-fleet scale-out record: N replicas behind the
        # gateway vs the same fleet drained to one — see
        # benchmarks/serve_benchmark.py --gateway
        extras["gateway_bench"] = {
            k: gateway_bench[k]
            for k in (
                "replicas", "clients", "work_us", "rounds", "window_s",
                "gateway_workers", "client_procs",
                "gateway_qps", "gateway_qps_1replica",
                "gateway_qps_1worker", "gateway_qps_nworker",
                "shard_profile",
                "gateway_p50_ms", "gateway_p99_ms", "gateway_scale_x",
                "gateway_shard_x", "pair_ratios", "shard_pair_ratios",
                "gateway_counters", "stages",
            )
            if k in gateway_bench
        }
    if scenario_bench and scenario_bench.get("phase") == "scenario_bench":
        # the scenario-plane record: heterogeneous-fleet ready-first
        # vs lock-step, plus the labelled serve traffic mix — see
        # benchmarks/scenario_benchmark.py
        extras["scenario_bench"] = {
            k: scenario_bench[k]
            for k in (
                "scenarios", "instances", "rounds", "window_s",
                "physics_us", "lockstep_steps_per_sec",
                "hetero_steps_per_sec", "scenario_hetero_x",
                "pair_ratios", "per_scenario_steps",
                "scenario_counters", "serve_mix", "serve_mix_p99_ms",
            )
            if k in scenario_bench
        }
    if ha_bench and ha_bench.get("phase") == "ha_bench":
        # the learner-failover record: async-checkpointing overhead
        # pairs + the SIGKILL recovery drill — see
        # benchmarks/ha_benchmark.py
        extras["ha_bench"] = {
            k: ha_bench[k]
            for k in (
                "window_s", "rounds", "ckpt_every_s",
                "ckpt_on_updates_per_sec", "ckpt_off_updates_per_sec",
                "ckpt_overhead_x", "pair_ratios",
                "learner_recovery_s", "recovery", "ha_counters",
                "stages",
            )
            if k in ha_bench
        }
    if autoscale_bench \
            and autoscale_bench.get("phase") == "autoscale_bench":
        # the autoscale record: decision-to-settle for a verified
        # scale-up and the drain scale-down's client-visible error
        # ledger — see benchmarks/autoscale_benchmark.py
        extras["autoscale_bench"] = {
            k: autoscale_bench[k]
            for k in (
                "replicas", "clients", "window_s",
                "resize_settle_s", "drain_settle_s",
                "drain_error_x", "drain_requests", "drain_errors",
                "autoscale_counters", "stages",
            )
            if k in autoscale_bench
        }
    if pipeline_bench \
            and pipeline_bench.get("phase") == "pipeline_bench":
        # the MPMD pipeline record: N-stage 1F1B over the 1-stage
        # same-harness baseline in interleaved windows — see
        # benchmarks/pipeline_benchmark.py
        extras["pipeline_bench"] = {
            k: pipeline_bench[k]
            for k in (
                "pipe_stages", "layers", "microbatches", "batch",
                "wire", "work_us", "rounds", "window_updates",
                "mpmd_updates_per_sec", "single_updates_per_sec",
                "pipe_mpmd_x", "pair_ratios", "pipe_counters",
                "stages",
            )
            if k in pipeline_bench
        }
    if weight_bench and weight_bench.get("phase") == "weight_bench":
        # the live-rollout cost record: publish -> first-serving-reply
        # swap latency and the QPS dip through the swap — see
        # benchmarks/weight_benchmark.py
        extras["weight_bench"] = {
            k: weight_bench[k]
            for k in (
                "clients", "publishes", "window_s", "snapshot_kb",
                "weight_swap_ms", "weight_swap_ms_p50",
                "weight_swap_qps_dip_x", "qps_steady",
                "swaps_observed", "swap_ms_all", "publish_ms_p50",
                "weight_counters", "stages",
            )
            if k in weight_bench
        }
    if feed_bound:
        # the feed ceiling, legacy vs arena assembly (trivial train step,
        # jax-free) — including the arena stage timings (arena_wait /
        # scatter / recycle), so the copy-elimination win is measurable
        # in the artifact rather than asserted
        extras["feed_bound"] = feed_bound
    if replay_bench:
        # the replay-path ceiling: ring append rate, columnar-vs-naive
        # sampling (replay_sample_x), and the FileRecorder buffered-write
        # before/after (record_buffered_x) — see benchmarks/replay_benchmark.py
        extras["replay_bench"] = replay_bench

    def pick(name):
        # prefer the accelerator child's phase; fall back to the cpu
        # fallback child's (suffixed _cpu by suite.py)
        return phases.get(name) or phases.get(name + "_cpu")

    hbm = pick("stream_to_hbm")
    train = pick("stream_to_train")
    seq = pick("seqformer_train")
    moe = pick("moe_compare")
    host = phases.get("host_stream")
    init = pick("device_init")
    canary = pick("tunnel_canary")
    fence = pick("fence_validation")
    if init:
        extras["device_init_s"] = init.get("seconds")
        extras["device"] = init.get("platform")
        extras["device_kind"] = init.get("device_kind")
    elif "device_init_timeout" in phases:
        extras["device"] = "none (init timed out)"
    if fence:
        # every timing below used a value-fetch fence; this carries the
        # per-run proof of which fences are even valid on this backend
        # (block_until_ready is phantom on the axon tunnel — r4 finding)
        extras["fence_validation"] = {
            "fence_ok": fence.get("fence_ok"),
            "fence_used": fence.get("fence_used"),
        }
    if canary:
        extras["tunnel"] = {
            k: canary[k]
            for k in ("rtt_ms", "put_mb_per_s", "batch_mb", "put_s",
                      "ceiling_method", "put_mb_per_s_raw",
                      "put_mb_per_s_rtt_adjusted")
            if k in canary
        }
    put_strat = pick("put_strategy")
    if put_strat:
        # winner AND loser ship together (VERDICT r4 next #6): the feed's
        # transfer granularity choice is evidence, not a hidden default
        extras["put_strategy"] = {
            k: put_strat[k]
            for k in ("winner", "chunked_over_whole", "chunks",
                      "whole_s", "chunked_s", "batch_mb")
            if k in put_strat
        }
    if moe:
        extras["moe_compare"] = {
            k: moe[k]
            for k in ("mlp", "dense", "topk", "topk_alt",
                      "topk_over_dense_mixture",
                      "consistent_dense_ge_mlp", "experts", "top_k",
                      "moe_dispatch", "partial")
            if k in moe
        }
    # bare-kernel verdicts (suite phase_kernel_microverdicts): the
    # cheapest on-chip witnesses of flash<=full / topk<=dense, banked
    # minutes into a live window — kept alongside (never instead of)
    # the train-step-level ratios, which supersede them in the headline
    kflash = pick("kernel_flash")
    kff = pick("kernel_flash_vs_full")
    kwin = pick("kernel_flash_windowed")
    if kflash or kff or kwin:
        ka = {}
        if kflash:
            ka["flash_step_ms"] = round(
                kflash["step_stats"]["step_s"] * 1e3, 3
            )
            ka["flash_compiled"] = kflash.get("compiled")
        if kff:
            for k in ("flash_step_ms", "full_step_ms",
                      "flash_over_full_kernel"):
                if k in kff:
                    ka[k] = kff[k]
        if kwin:
            for k in ("window", "windowed_step_ms",
                      "windowed_over_flash"):
                if k in kwin:
                    ka[k] = kwin[k]
        extras["kernel_attn"] = ka
    kint8 = pick("int8_infer")
    if kint8:
        extras["int8_infer"] = {
            k: kint8[k]
            for k in ("bf16_step_ms", "int8_step_ms", "int8_over_bf16")
            if k in kint8
        }
    ktopk = pick("kernel_topk")
    ktd = pick("kernel_topk_vs_dense")
    if ktopk or ktd:
        km = {}
        if ktopk:
            km["topk_step_ms"] = round(
                ktopk["step_stats"]["step_s"] * 1e3, 3
            )
        if ktd:
            for k in ("topk_step_ms", "dense_step_ms",
                      "topk_over_dense_kernel"):
                if k in ktd:
                    km[k] = ktd[k]
        extras["kernel_moe"] = km
    if host:
        extras["host_stream_images_per_sec"] = host["items_per_sec"]
    if hbm:
        extras["stream_to_hbm_images_per_sec"] = hbm["items_per_sec"]
        extras["stream_to_hbm_windows"] = hbm.get("items_per_sec_windows")
        extras["stream_to_hbm_stages"] = hbm.get("stages")
    # no _cpu fallback for the gate-off probe: the comparison is only
    # honest against the SAME child's gate-on number (same platform,
    # same fleet) — a cross-child pairing would present a tpu-vs-cpu
    # gap as the measured gate effect
    gateoff = phases.get("stream_to_hbm_gateoff")
    if (gateoff and hbm
            and gateoff.get("platform") == hbm.get("platform")):
        extras["stream_to_hbm_gateoff_images_per_sec"] = gateoff[
            "items_per_sec"
        ]
        if "items_per_sec_windows" in gateoff:
            extras["stream_to_hbm_gateoff_windows"] = gateoff[
                "items_per_sec_windows"
            ]
    if train:
        extras["train_duty_cycle"] = train.get("train_duty_cycle")
        if train.get("duty_cycle_invalid"):
            extras["duty_cycle_invalid"] = True
        extras["detector_step_ms"] = round(train["step_s"] * 1e3, 3)
        extras["stream_to_train_windows"] = train.get(
            "items_per_sec_windows"
        )
        extras["stream_to_train_stages"] = train.get("stages")
        extras["detector_step_stats"] = train.get("step_stats")
        for k in ("step_flops_analytic", "step_flops_xla", "mfu",
                  "mfu_invalid"):
            if k in train:
                extras[f"detector_{k}"] = train[k]
        # the wire's ceiling for this phase, from the same-run canary:
        # no pipeline can stream images to the device faster than the
        # measured fenced put bandwidth.  Only comparable when both
        # numbers come from the same child/device — a TPU canary must
        # not be divided into a cpu-fallback child's local throughput
        if (canary and "put_mb_per_s" in canary
                and train.get("platform") == canary.get("platform")):
            image_mb = (
                train.get("width", 640) * train.get("height", 480)
                * train.get("channels", 4) / 1e6
            )
            wire_limit = canary["put_mb_per_s"] / image_mb
            extras["wire_limit_images_per_sec"] = round(wire_limit, 1)
            extras["pipeline_wire_efficiency"] = round(
                train["items_per_sec"] / wire_limit, 3
            )
            # VERDICT r4 weak #2: this ratio measures the framework only
            # when the wire is the binding resource.  On a cpu fallback
            # the "wire" is loopback (GB/s) and the train step binds, so
            # delivered/ceiling reads ~0.01 for reasons that have nothing
            # to do with the pipeline — label it.
            duty = train.get("train_duty_cycle")
            duty_invalid = bool(train.get("duty_cycle_invalid"))
            train_bound = (duty is not None and duty >= 0.9
                           and not duty_invalid)
            meaningful = (train.get("platform") == "tpu"
                          and not train_bound and not duty_invalid)
            extras["wire_efficiency_meaningful"] = meaningful
            if not meaningful:
                if duty_invalid:
                    caveat = ("duty cycle invalid; binding resource "
                              "unknown — ratio untrustworthy")
                elif train_bound:
                    caveat = ("train step binds (duty>=0.9); ratio "
                              "reflects compute, not the feed")
                else:
                    caveat = ("non-tpu loopback wire; ratio does not "
                              "measure the pipeline")
                extras["wire_efficiency_caveat"] = caveat
    if seq:
        extras["seqformer"] = {
            k: seq[k]
            for k in (
                "tokens_per_sec",
                "train_duty_cycle",
                "duty_cycle_invalid",
                "attn",
                "full_attn_step_s",
                "flash_over_full",
                "mfu",
                "mfu_invalid",
                "step_s",
                "step_stats",
                "device_kind",
                "model_flops_per_sec",
                "step_flops_analytic",
                "step_flops_xla",
                "items_per_sec_windows",
                "stages",
                "window_skipped",
                "stream_pending",
                "batches",
            )
            if k in seq
        }
    if rl:
        extras["rl_steps_per_sec"] = rl.get("value")
        extras["rl_vs_baseline"] = rl.get("vs_baseline")
        extras["rl_includes_physics"] = rl.get("includes_physics", False)
    if rl_physics:
        extras["rl_steps_per_sec_physics250us"] = rl_physics.get("value")
        extras["rl_vs_baseline_physics250us"] = rl_physics.get("vs_baseline")
    if rl_pipelined:
        extras["rl_pipeline_depth"] = rl_pipelined.get("pipeline_depth")
        if rl_pipelined.get("metric") == "rl_pipelined_x":
            # --compare line: the ratio IS the value (median of
            # interleaved lock-step/pipelined window pairs on one fleet —
            # the serialization tax the async path recovered), with both
            # absolute medians alongside
            extras["rl_pipelined_x"] = rl_pipelined.get("value")
            extras["rl_steps_per_sec_pipelined"] = rl_pipelined.get(
                "pipelined_steps_per_sec"
            )
        else:
            # single-mode pipelined line: ratio against the lock-step
            # phase (two separate runs; drift-prone, kept for compat)
            extras["rl_steps_per_sec_pipelined"] = rl_pipelined.get("value")
            base = (rl_physics or {}).get("value")
            if rl_pipelined.get("value") and base:
                extras["rl_pipelined_x"] = round(
                    rl_pipelined["value"] / base, 3
                )
    if rl_sharded and rl_sharded.get("metric") == "rl_sharded_x":
        # the Sebulba sharded actor-learner ratio (4 fleets feeding a
        # P('data')-sharded learner over the 8-fake-device MULTICHIP
        # harness vs single fleet/device; interleaved window pairs,
        # simulation-bound physics — see docs/sharded_rl.md), with both
        # absolute medians and the multi-fleet health aggregate
        extras["rl_sharded_x"] = rl_sharded.get("value")
        extras["rl_sharded_config"] = {
            k: rl_sharded[k]
            for k in ("mesh_devices", "fleets", "instances_per_fleet",
                      "total_envs", "physics_us", "pair_ratios",
                      "single_env_steps_per_sec",
                      "sharded_env_steps_per_sec")
            if k in rl_sharded
        }
        if "fleet_health" in rl_sharded:
            extras["rl_sharded_fleet_health"] = rl_sharded["fleet_health"]

    def dims(p):
        # cpu-fallback phases may run shrunken frames, and the wire
        # carries RGB by default since round 5 (RGBA before): name the
        # metric by what was actually measured, channels included — a
        # 25%-lighter payload must never ride under a pre-r5 metric name
        return (f"cube{p.get('width', 640)}x{p.get('height', 480)}"
                f"x{p.get('channels', 4)}")

    def full_res(p):
        return (p.get("width", 640), p.get("height", 480)) == (640, 480)

    if train:
        ips = train["items_per_sec"]
        # a shrunken-frame fallback is NOT comparable to the reference's
        # 640x480 number: keep it, but degraded
        metric = f"{dims(train)}_images_per_sec_stream_to_train"
        degraded = not full_res(train)
        if "channels" in train:
            extras["wire_channels"] = train["channels"]
    elif hbm:
        ips = hbm["items_per_sec"]
        metric, degraded = f"{dims(hbm)}_images_per_sec_stream_to_hbm", True
    elif host:
        ips = host["items_per_sec"]
        metric, degraded = "cube640x480x3_images_per_sec_host_stream_only", True
    else:
        sys.stderr.write("no suite phases arrived; host-only fallback\n")
        ips = host_fallback() if host_fallback else 0.0
        metric, degraded = "cube640x480x3_images_per_sec_host_stream_only", True

    out = {
        "metric": metric,
        "value": round(ips, 2),
        "unit": "images/sec",
        "vs_baseline": round(ips * REF_SEC_PER_IMAGE, 3),
        "train_degraded": degraded,
    }
    wire_limit = extras.get("wire_limit_images_per_sec")
    if wire_limit is not None and wire_limit * REF_SEC_PER_IMAGE < 1.0:
        # the measured host->device wire caps below the reference's rate:
        # no framework could reach vs_baseline 1.0 through this link, so
        # the honest comparison is pipeline_wire_efficiency (how much of
        # the physically available wire the pipeline delivers into train)
        out["wire_bound"] = True
    if not metric.startswith("cube640x480"):
        # reference's 0.012 s/image is 640x480; shrunken-frame throughput
        # must not be read as a baseline multiple
        out["vs_baseline_comparable"] = False
    out.update(extras)
    return out


if __name__ == "__main__":
    main()
