"""Driver benchmark: one JSON line on stdout.

Measures the blendjax end-to-end streaming pipeline on the reference's own
headline configuration (``Readme.md:92``: Cube scene 640x480 RGBA, 4
producer instances, 4 workers, batch 8 — 0.012 sec/image there): synthetic
producers speaking the real wire protocol -> fan-in PULL -> threaded batch
loader -> double-buffered device_put into TPU HBM -> detector train step
per batch.  Rendering itself is excluded on both sides of the comparison's
consumer path (the reference number includes Blender's render; ours uses
synthetic frames because Blender cannot run in this image), so treat
``vs_baseline`` as transport+train throughput vs the reference's full
pipeline ceiling.

``vs_baseline`` = measured images/sec over the reference's 4-instance
83.3 images/sec (1 / 0.012).
"""

from __future__ import annotations

import json
import sys

#: reference Readme.md:92 — 4 instances, 0.012 sec/image
BASELINE_IMAGES_PER_SEC = 1.0 / 0.012


def main():
    sys.path.insert(0, ".")
    import os

    # honor $JAX_PLATFORMS even when sitecustomize pre-registers a backend
    plat = os.environ.get("JAX_PLATFORMS")
    if plat:
        import jax

        try:
            jax.config.update("jax_platforms", plat)
        except Exception:
            pass

    from benchmarks.benchmark import parse_args, run

    args = parse_args(
        [
            "--instances", "4",
            "--workers", "4",
            "--batch", "8",
            "--items", "100000000",   # stream until the window closes
            "--seconds", "45",         # fixed measurement window
            "--warmup-deadline", "420",  # tunnel compiles can be slow
        ]
    )
    result = run(args)
    suffix = "stream_only" if result.get("train_degraded") else "stream_to_train"
    print(
        json.dumps(
            {
                "metric": f"cube640x480_images_per_sec_{suffix}",
                "value": round(result["images_per_sec"], 2),
                "unit": "images/sec",
                "vs_baseline": round(
                    result["images_per_sec"] / BASELINE_IMAGES_PER_SEC, 3
                ),
            }
        )
    )


if __name__ == "__main__":
    main()
