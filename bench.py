"""Driver benchmark: one JSON line on stdout, guaranteed.

Measures the blendjax end-to-end streaming pipeline on the reference's own
headline configuration (``Readme.md:92``: Cube scene 640x480 RGBA, 4
producer instances, 4 workers, batch 8 — 0.012 sec/image there): synthetic
producers speaking the real wire protocol -> fan-in PULL -> threaded batch
loader -> double-buffered device_put into TPU HBM -> detector train step per
batch.  Rendering is excluded (Blender cannot run in this image), so
``vs_baseline`` compares transport+train throughput against the reference's
full-pipeline number.

Robustness: the jax measurement runs in a child process under a hard
deadline (TPU-tunnel device init / first compile can stall for minutes).
If the child cannot deliver, a host-only pipeline measurement (recv +
collate, no jax) is reported instead — the driver always gets its line.

``vs_baseline`` = measured images/sec x 0.012 (reference 4-instance
sec/image), i.e. >1.0 beats the reference's best published configuration.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

CHILD_BUDGET_S = 540  # warmup deadline (420) + window (45) + slack


def host_only_fallback(seconds=10.0):
    """Measure the host half of the pipeline (no jax): producers -> fan-in
    recv -> collate."""
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from benchmarks.benchmark import launch_producers

    from blendjax.btt.dataset import RemoteIterableDataset
    from blendjax.btt.loader import BatchLoader

    addrs, procs = launch_producers(4, raw=True, width=640, height=480)
    try:
        ds = RemoteIterableDataset(addrs, max_items=10**9, timeoutms=60000)
        with BatchLoader(ds, batch_size=8, num_workers=4) as loader:
            it = iter(loader)
            for _ in range(8):
                next(it)  # warmup
            t0 = time.perf_counter()
            n = 0
            while time.perf_counter() - t0 < seconds:
                next(it)
                n += 1
            dt = time.perf_counter() - t0
        return (n * 8) / dt
    finally:
        for p in procs:
            p.terminate()
        for p in procs:
            try:
                p.wait(timeout=5)
            except subprocess.TimeoutExpired:
                p.kill()


def main():
    here = os.path.dirname(os.path.abspath(__file__))
    sys.path.insert(0, here)
    # fastest transport available: native shared-memory rings + zero-copy
    # raw-buffer framing; tcp+pickle only if the native lib can't build
    try:
        from blendjax.native import native_available

        native = native_available()
    except Exception:
        native = False
    # Fleet size follows the host: the reference's 4 instances x 4 workers
    # assumes cores to run them on; on a 1-2 core TPU-VM frontend the
    # process thrash halves throughput, so scale the fleet down and lean on
    # deep device prefetch instead (the tunnel pipelines ~12 batches well).
    cores = os.cpu_count() or 1
    instances = 4 if cores >= 4 else 1
    workers = 4 if cores >= 4 else 1
    cmd = [
        sys.executable,
        os.path.join(here, "benchmarks", "benchmark.py"),
        "--instances", str(instances),
        "--workers", str(workers),
        "--batch", "8",
        "--items", "100000000",
        "--seconds", "45",
        "--warmup-deadline", "420",
        "--prefetch", "12",
        "--json",
    ]
    if native:
        # raw framing only pays off on shm (tcp multipart adds syscalls)
        cmd += ["--raw", "--transport", "shm"]
    else:
        cmd += ["--pickle"]  # tcp fallback: single-frame pickle is faster
    # child needs blendjax importable; child_env() prepends the repo root
    # without replacing PYTHONPATH, which may carry the TPU plugin
    # registration (axon sitecustomize)
    from blendjax.btt.launcher import child_env

    env = child_env()
    try:
        out = subprocess.run(
            cmd,
            capture_output=True,
            text=True,
            timeout=CHILD_BUDGET_S,
            cwd=here,
            env=env,
        )
        for line in reversed(out.stdout.strip().splitlines()):
            line = line.strip()
            if line.startswith("{") and '"metric"' in line:
                print(line)
                return
        sys.stderr.write(
            f"benchmark child exited {out.returncode} without JSON; "
            f"stderr tail: {out.stderr[-2000:]}\n"
        )
    except subprocess.TimeoutExpired:
        sys.stderr.write("benchmark child exceeded deadline; falling back\n")

    ips = host_only_fallback()
    print(
        json.dumps(
            {
                "metric": "cube640x480_images_per_sec_host_stream_only",
                "value": round(ips, 2),
                "unit": "images/sec",
                "vs_baseline": round(ips * 0.012, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
